"""Performance-attribution observability (ISSUE 11): step-time
decomposition, compile forensics, and their integration surface.

Covers the acceptance bars: per-window perf segments TILE the measured
window (live single-device AND live 8-virtual-device mesh runs, within
5% — by construction they tile exactly), injected drills classify to the
right named cause as once-latched events with diagnostics on disk, the
steady-state-recompile gate fires on a shape leak and stays quiet on
healthy runs, the perf-observer tax is < 2% of p50 step (PR 8's
min-of-tight-loop bound methodology), and the emitted stream passes
``obs_report --check`` with the perf + compile sections rendered.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs import (
    CompileWatcher,
    DiagnosticsCapture,
    FlightRecorder,
    HealthWatchdog,
    PerfObserver,
    SpanTracker,
    bind_health,
)
from induction_network_on_fewrel_tpu.obs.perf import TILE_SEGMENTS
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train import FewShotTrainer
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import obs_report  # noqa: E402

L = 16


def _tiny_cfg(**kw):
    base = dict(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", val_step=0, lr=1e-2,
        loss="ce",
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _setup(cfg, seed=0):
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=20, vocab_size=300, seed=seed
    )
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(
        ds, tok, n=cfg.n, k=cfg.k, q=cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate, seed=seed,
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    return model, sampler


def _tiles_ms(rec):
    return sum(rec[f"{seg}_ms"] for seg in TILE_SEGMENTS)


def _perf_records(run_dir):
    recs = [
        json.loads(line)
        for line in (run_dir / "metrics.jsonl").read_text().splitlines()
    ]
    return recs, [r for r in recs if r["kind"] == "perf"]


# --- the tiling invariant (live runs) --------------------------------------


def test_perf_segments_tile_window_live_run(tmp_path, capsys):
    """Acceptance: on a live run, every kind="perf" window's segments sum
    to the measured window within 5% (they tile EXACTLY by construction
    — ``other`` is the residual), step_ms agrees with window_s/steps, and
    the report renders the perf + compile sections with --check green."""
    cfg = _tiny_cfg()
    model, sampler = _setup(cfg)
    logger = MetricsLogger(tmp_path, quiet=True)
    cw = CompileWatcher(logger=logger).install()
    perf = PerfObserver(logger=logger, compile_watcher=cw)
    trainer = FewShotTrainer(
        model, cfg, sampler, logger=logger, perf=perf, compile_watcher=cw
    )
    try:
        trainer.train(num_iters=110)   # window=50 -> >= 2 full windows
    finally:
        trainer.close()

    recs, perf_recs = _perf_records(tmp_path)
    assert len(perf_recs) >= 2
    for rec in perf_recs:
        window_ms = rec["window_s"] * 1e3
        assert abs(_tiles_ms(rec) - window_ms) <= 0.05 * window_ms
        # The restated sum agrees with the tiles (report cross-check).
        assert abs(rec["segments_sum_ms"] - _tiles_ms(rec)) < 0.01
        assert rec["step_ms"] == pytest.approx(
            window_ms / rec["steps"], rel=1e-3
        )
        # A live step spends real time in dispatch; the decomposition
        # must attribute it (not dump everything into ``other``).
        assert rec["host_dispatch_ms"] > 0
    # Compile forensics observed the train-step compile, attributed to
    # the dispatch span, phase=warmup — and the steady gate stayed quiet.
    comp = [r for r in recs if r["kind"] == "compile"]
    ts = [c for c in comp if "train" in c["fn"]]
    assert ts and ts[0]["trigger"] == "train/dispatch"
    assert ts[0]["phase"] == "warmup"
    assert cw.steady_recompiles == 0
    assert not any(
        r["kind"] == "health" and r.get("event") == "recompile_burst"
        for r in recs
    )

    assert obs_report.main([str(tmp_path), "--check"]) == 0
    assert obs_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "-- perf --" in out and "tiles_ok_frac: 1.0" in out
    assert "-- compile --" in out and "by_phase" in out


def test_perf_segments_tile_on_dp8_mesh_run(tmp_path):
    """Acceptance: the tiling invariant holds on a LIVE 8-virtual-device
    CPU-mesh training run (injected sharded step, the production mesh
    path) — segments sum to the measured window within 5%."""
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_train_step,
        shard_state,
    )
    from induction_network_on_fewrel_tpu.models.build import (
        batch_to_model_inputs,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = _tiny_cfg(batch_size=8, metric_window_calls=25)
    model, sampler = _setup(cfg)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    state0 = init_state(model, cfg, sup, qry)
    mesh = make_mesh(dp=8)
    step = make_sharded_train_step(model, cfg, mesh, state0)
    logger = MetricsLogger(tmp_path, quiet=True)
    perf = PerfObserver(logger=logger)
    trainer = FewShotTrainer(
        model, cfg, sampler, logger=logger, perf=perf,
        train_step=step, initial_state=shard_state(state0, mesh), mesh=mesh,
    )
    try:
        trainer.train(num_iters=60)
    finally:
        trainer.close()
    _, perf_recs = _perf_records(tmp_path)
    assert perf_recs, "mesh run emitted no kind='perf' windows"
    for rec in perf_recs:
        window_ms = rec["window_s"] * 1e3
        assert abs(_tiles_ms(rec) - window_ms) <= 0.05 * window_ms
        assert rec["host_dispatch_ms"] > 0


# --- out-of-band classification + drills -----------------------------------


def _drive_windows(perf, tracker, n, sample_s, dispatch_s, steps=3,
                   ckpt_s=0.0, start=0):
    step = start
    out = []
    for _ in range(n):
        for _ in range(steps):
            with tracker.span("train/sample"):
                time.sleep(sample_s)
            with tracker.span("train/dispatch"):
                time.sleep(dispatch_s)
        if ckpt_s:
            with tracker.span("train/checkpoint"):
                time.sleep(ckpt_s)
        step += steps
        out.append(perf.observe_window(step))
    return out


def test_feed_stall_drill_classifies_and_latches(tmp_path):
    """A data-wait-dominated out-of-band window classifies to feed_stall,
    emits ONE once-latched critical with diagnostics on disk (span
    snapshot via DiagnosticsCapture + flight dump via the health
    emitter), holds the latch through consecutive slow windows, and
    re-arms after an in-band window."""
    tracker = SpanTracker(capacity=512, xplane_bridge=False)
    recorder = FlightRecorder(out_dir=tmp_path, tracker=tracker)
    logger = MetricsLogger(tmp_path, quiet=True)
    wd = HealthWatchdog(logger=logger, recorder=recorder)
    capture = DiagnosticsCapture(out_dir=tmp_path, recorder=recorder,
                                 tracker=tracker, profile=False)
    perf = PerfObserver(logger=logger, tracker=tracker, capture=capture,
                        on_event=wd._emit)
    perf.begin(0)
    _drive_windows(perf, tracker, 4, sample_s=0.002, dispatch_s=0.006)
    assert not perf.events
    slow = _drive_windows(perf, tracker, 2, sample_s=0.02,
                          dispatch_s=0.006, start=12)
    assert all(r["oob"] for r in slow)
    assert [r["cause"] for r in slow] == ["feed_stall", "feed_stall"]
    # Once-latched: two slow windows, ONE event.
    assert [e.data["cause"] for e in perf.events] == ["feed_stall"]
    assert wd.tripped
    # Diagnostics on disk: flight dump (health emitter) + span snapshot.
    assert (tmp_path / "flight_recorder.json").exists()
    assert list(perf.captured.values())[0]["span_snapshot"] is not None
    assert (tmp_path / "slo_spans_1.json").exists()
    # In-band window re-arms; the next slow window is a NEW incident.
    _drive_windows(perf, tracker, 2, sample_s=0.002, dispatch_s=0.006,
                   start=18)
    _drive_windows(perf, tracker, 1, sample_s=0.02, dispatch_s=0.006,
                   start=24)
    assert len(perf.events) == 2
    perf.close()
    logger.close()


def test_checkpoint_spike_and_contention_causes():
    """A checkpoint-dominated window classifies checkpoint_spike; a
    uniformly-slower window with the same segment mix falls through to
    neighbor_contention (the residual cause)."""
    tracker = SpanTracker(capacity=512, xplane_bridge=False)
    perf = PerfObserver(tracker=tracker)
    perf.begin(0)
    _drive_windows(perf, tracker, 3, sample_s=0.001, dispatch_s=0.005)
    spike = _drive_windows(perf, tracker, 1, sample_s=0.001,
                           dispatch_s=0.005, ckpt_s=0.03, start=9)[0]
    assert spike["oob"] and spike["cause"] == "checkpoint_spike"
    _drive_windows(perf, tracker, 1, sample_s=0.001, dispatch_s=0.005,
                   start=12)   # re-arm
    slow = _drive_windows(perf, tracker, 1, sample_s=0.002,
                          dispatch_s=0.012, start=15)[0]
    assert slow["oob"] and slow["cause"] == "neighbor_contention"
    assert [e.data["cause"] for e in perf.events] == [
        "checkpoint_spike", "neighbor_contention"
    ]
    perf.close()


def test_recompile_burst_cause_beats_other_classifiers():
    """Compiles that EXPLAIN the window's excess classify recompile_burst
    ahead of every other cause — but a tiny utility-pjit compile (the
    obs/compile.py gate_min_s case) must NOT mask the true cause."""
    tracker = SpanTracker(capacity=512, xplane_bridge=False)

    class _FakeCW:
        compiles = 0
        compile_s_total = 0.0

    cw = _FakeCW()
    perf = PerfObserver(tracker=tracker, compile_watcher=cw)
    perf.begin(0)
    _drive_windows(perf, tracker, 3, sample_s=0.001, dispatch_s=0.005)
    cw.compiles, cw.compile_s_total = 1, 0.060   # dominates the excess
    slow = _drive_windows(perf, tracker, 1, sample_s=0.02,
                          dispatch_s=0.005, start=9)[0]
    assert slow["oob"] and slow["cause"] == "recompile_burst"
    assert slow["compiles"] == 1.0
    # Re-arm, then a feed-stalled window carrying only a ~1 ms utility
    # compile: the stall, not the compile, is the named cause.
    _drive_windows(perf, tracker, 1, sample_s=0.001, dispatch_s=0.005,
                   start=12)
    cw.compiles, cw.compile_s_total = 2, 0.061
    masked = _drive_windows(perf, tracker, 1, sample_s=0.02,
                            dispatch_s=0.005, start=15)[0]
    assert masked["oob"] and masked["cause"] == "feed_stall"
    perf.close()


def test_nan_drill_classifies_non_finite_not_perf(tmp_path):
    """The --nan_inject_step drill must classify to the watchdog's
    non_finite cause — NOT to a perf cause (a NaN loss is a numerics
    incident; the perf observer stays quiet on a healthy-speed run)."""
    cfg = _tiny_cfg(nan_inject_step=60)
    model, sampler = _setup(cfg)
    logger = MetricsLogger(tmp_path, quiet=True)
    recorder = FlightRecorder(out_dir=tmp_path)
    wd = HealthWatchdog(recorder=recorder)
    perf = PerfObserver(logger=logger, on_event=wd._emit)
    trainer = FewShotTrainer(
        model, cfg, sampler, logger=logger, watchdog=wd, recorder=recorder,
        perf=perf,
    )
    try:
        trainer.train(num_iters=110)
    finally:
        trainer.close()
    assert any(e.event == "non_finite" for e in wd.events)
    assert not perf.events, (
        "the NaN drill must not read as a perf regression"
    )
    assert (tmp_path / "flight_recorder.json").exists()


# --- compile forensics -----------------------------------------------------


def test_compile_watcher_records_and_gate(tmp_path):
    """Every compile lands with fn/shapes/elapsed/trigger; the gated
    steady-recompile fires ONCE (once-latched) on a seen fn compiling a
    new shape after arm_steady, and tiny shape variants stay ungated."""
    logger = MetricsLogger(tmp_path, quiet=True)
    events = []
    with CompileWatcher(logger=logger, gate_min_s=0.0) as cw:
        bind_health(cw, events.append)

        @jax.jit
        def probe_fn(x):
            return x * 2 + 1

        probe_fn(jnp.ones((4, 4)))
        probe_fn(jnp.ones((4, 4)))       # cache hit: nothing observed
        snap = cw.snapshot()
        rec = [r for r in snap["records"] if r["fn"] == "probe_fn"]
        assert rec and rec[0]["phase"] == "warmup"
        assert "float32[4,4]" in rec[0]["shapes"]
        assert rec[0]["elapsed_s"] > 0
        assert cw.steady_recompiles == 0 and not events

        cw.arm_steady()
        probe_fn(jnp.ones((8, 4)))       # shape leak: gated recompile
        assert cw.steady_recompiles == 1
        assert [e.event for e in events] == ["recompile_burst"]
        assert events[0].severity == "critical"
        assert events[0].data["fn"] == "probe_fn"
        probe_fn(jnp.ones((16, 4)))      # still latched: ONE incident
        assert cw.steady_recompiles == 2
        assert len(events) == 1
        cw.rearm()
        probe_fn(jnp.ones((32, 4)))
        assert len(events) == 2
    before = cw.compiles
    probe_fn(jnp.ones((64, 4)))          # uninstalled: not observed
    assert cw.compiles == before
    logger.close()
    # The stream validates (kind="compile" is a known kind).
    assert obs_report.main([str(tmp_path), "--check"]) == 0


def test_compile_gate_min_elapsed_filters_utility_pjits():
    """The gate must ignore sub-threshold shape variants (single-
    primitive utility pjits legitimately compile many shapes): with the
    default gate_min_s, a fast compile of a new shape is recorded as a
    shape variant but never counts as a steady recompile."""
    cw = CompileWatcher(gate_min_s=10.0).install()   # nothing is gated
    try:
        cw.arm_steady()

        @jax.jit
        def tiny_fn(x):
            return x + 1

        tiny_fn(jnp.ones((2,)))
        tiny_fn(jnp.ones((3,)))          # new shape, fast compile
        assert cw.shape_variant_compiles >= 1
        assert cw.steady_recompiles == 0
    finally:
        cw.uninstall()


# --- observer tax ----------------------------------------------------------


def test_perf_observer_tax_under_2pct_of_p50_step(tmp_path):
    """The per-step cost of the observer is its per-window work amortized
    over the window (there is ZERO per-step instrumentation beyond the
    spans that already exist). Bound: min-of-tight-loop observe_window
    cost over a FULL ring (the worst case the window scan can see),
    divided by the window's steps, vs the measured p50 step of a live
    tiny run — the contention-immune spelling PR 8's tracing gate
    settled on (a wall-clock A/B cannot resolve microseconds on this
    sandbox)."""
    cfg = _tiny_cfg()
    model, sampler = _setup(cfg)
    logger = MetricsLogger(tmp_path, quiet=True)
    perf = PerfObserver(logger=logger)
    trainer = FewShotTrainer(model, cfg, sampler, logger=logger, perf=perf)
    try:
        trainer.train(num_iters=110)
    finally:
        trainer.close()
    _, perf_recs = _perf_records(tmp_path)
    step_ms = sorted(r["step_ms"] for r in perf_recs)[len(perf_recs) // 2]

    # Worst-case observe cost: a FULL tracker ring to scan.
    tracker = SpanTracker(capacity=4096, xplane_bridge=False)
    for _ in range(4096):
        with tracker.span("train/dispatch"):
            pass
    obs = PerfObserver(tracker=tracker)   # no logger: measure the scan
    obs.begin(0)
    window_steps = 50                     # the trainer's minimum window
    best = float("inf")
    step = 0
    for _ in range(20):
        step += window_steps
        t0 = time.perf_counter()
        obs.observe_window(step)
        best = min(best, time.perf_counter() - t0)
    obs.close()
    per_step_ms = best * 1e3 / window_steps
    frac = per_step_ms / step_ms
    assert frac < 0.02, (
        f"perf-observer tax {per_step_ms:.4f} ms/step is "
        f"{frac:.2%} of p50 step {step_ms:.3f} ms (bar 2%)"
    )
