"""Delta ring checkpoints (train/checkpoint.py, round 6).

The recovery ring writes base + touched-row deltas for lazy-embed states
(the ~242 MB of table+moment d2h that dominated boundary cost, BASELINE.md
round 5). The contract under test:

* resume-from-delta is TRAJECTORY-EQUAL: restore_latest reassembles the
  bitwise-identical state, and training continued from it matches the
  uninterrupted run exactly;
* non-lazy states keep full ring saves; tiny tables whose delta exceeds
  half the rows re-base instead of writing a larger-than-full delta;
* the divergence guard's purge covers base and delta slots;
* ring saves emit kind="ckpt" telemetry that obs_report's schema gate
  accepts.
"""

import jax
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager
from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

# Vocab >> corpus so the touched-row set stays far under the half-table
# rebase threshold and ring saves actually take the delta path.
VOCAB = 402
CFG = ExperimentConfig(
    encoder="cnn", n=3, k=2, q=2, batch_size=2, max_length=12,
    vocab_size=VOCAB, hidden_size=16, lr=3e-3, weight_decay=0.0,
    embed_optimizer="lazy", compute_dtype="float32", ckpt_stage="off",
)
STEPS = 8


@pytest.fixture(scope="module")
def fixture():
    vocab = make_synthetic_glove(vocab_size=VOCAB - 2)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=6, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    sampler = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=3)
    batches = [
        batch_to_model_inputs(sampler.sample_batch()) for _ in range(STEPS + 2)
    ]
    model = build_model(CFG, glove_init=vocab.vectors)
    return model, batches


def _assert_trees_equal(a, b):
    for (pa, va), (_, vb) in zip(
        jax.tree_util.tree_flatten_with_path(a)[0],
        jax.tree_util.tree_flatten_with_path(b)[0],
    ):
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb),
            err_msg=f"leaf {jax.tree_util.keystr(pa)} diverged",
        )


def test_delta_resume_trajectory_equality(fixture, tmp_path):
    """Train -> base save -> train -> DELTA save -> (new manager, as a
    resumed process would build) restore -> continue == the uninterrupted
    run, bitwise, every leaf — the ISSUE 3 acceptance bar."""
    model, batches = fixture
    step_fn = make_train_step(model, CFG)
    state = init_state(model, CFG, batches[0][0], batches[0][1])
    template = jax.device_get(state)

    mgr = CheckpointManager(tmp_path, CFG)
    for sup, qry, lab in batches[:4]:
        state, _ = step_fn(state, sup, qry, lab)
    info_base = mgr.save_latest(4, state)
    mgr.wait()
    for sup, qry, lab in batches[4:6]:
        state, _ = step_fn(state, sup, qry, lab)
    info_delta = mgr.save_latest(6, state)
    mgr.close()
    assert info_base["mode"] == "base"
    assert info_delta["mode"] == "delta"
    # The steady-state boundary payload is a small fraction of the full
    # save — the byte diet this feature exists for. At this toy shape the
    # non-embedding head dominates both, so compare the EMBEDDING portion:
    # delta rows << table rows.
    assert info_delta["rows"] < VOCAB // 4

    # Fresh manager on the same dir = a resumed process.
    mgr2 = CheckpointManager(tmp_path, CFG)
    restored, step_no = mgr2.restore_latest(template)
    assert step_no == 6
    _assert_trees_equal(jax.device_get(state), restored)

    # Continue BOTH from the restore and from the live state: identical.
    cont_live, _ = step_fn(state, *batches[6])
    cont_rest, _ = step_fn(restored, *batches[6])
    _assert_trees_equal(jax.device_get(cont_live), jax.device_get(cont_rest))

    # And the post-resume ring save is a delta against the SAME base the
    # directory already held (no fresh base: the restore re-armed it).
    info_resumed = mgr2.save_latest(7, cont_rest)
    assert info_resumed["mode"] == "delta"
    mgr2.wait()
    restored2, step_no2 = mgr2.restore_latest(template)
    assert step_no2 == 7
    _assert_trees_equal(jax.device_get(cont_rest), restored2)
    mgr2.close()


def test_zero_row_delta_saves_and_restores(fixture, tmp_path):
    """A boundary where NO embedding row moved (identical state saved at
    a later step) must still produce a valid delta: orbax cannot store
    0-length arrays, and a poisoned saver error would kill every later
    save on the manager (round-6 review finding — the save pads to one
    no-op row)."""
    model, batches = fixture
    step_fn = make_train_step(model, CFG)
    state = init_state(model, CFG, batches[0][0], batches[0][1])
    for sup, qry, lab in batches[:2]:
        state, _ = step_fn(state, sup, qry, lab)
    mgr = CheckpointManager(tmp_path, CFG)
    assert mgr.save_latest(2, state, force=True)["mode"] == "base"
    mgr.wait()
    # Same state, later step: zero changed rows.
    info = mgr.save_latest(3, state, force=True)
    assert info["mode"] == "delta"
    mgr.wait()  # must not surface a saver error
    # The manager stays healthy for further saves…
    state2, _ = step_fn(state, *batches[2])
    assert mgr.save_latest(4, state2, force=True)["mode"] == "delta"
    mgr.wait()
    # …and the zero-row slot restores bitwise.
    template = jax.device_get(init_state(model, CFG, batches[0][0], batches[0][1]))
    restored, step_no = mgr.restore_latest(template)
    assert step_no == 4
    _assert_trees_equal(jax.device_get(state2), restored)
    mgr.close()


def test_non_lazy_states_keep_full_ring(fixture, tmp_path):
    """A shared-optimizer state has no emb leaves: ring saves stay full
    orbax saves in the legacy slot; no base/delta dirs are populated."""
    cfg = CFG.replace(embed_optimizer="shared")
    vocab = make_synthetic_glove(vocab_size=VOCAB - 2)
    model = build_model(cfg, glove_init=vocab.vectors)
    _, batches = fixture
    state = jax.device_get(init_state(model, cfg, batches[0][0], batches[0][1]))

    mgr = CheckpointManager(tmp_path, cfg)
    info = mgr.save_latest(5, state, force=True)
    mgr.wait()
    assert info["mode"] == "full"
    assert mgr.latest_mngr.latest_step() == 5
    assert mgr.ring_base_mngr.latest_step() is None
    restored, step_no = mgr.restore_latest(state)
    assert step_no == 5
    _assert_trees_equal(state, restored)
    mgr.close()


def test_ckpt_delta_off_forces_full(fixture, tmp_path):
    """ckpt_delta="off": lazy states too write full ring saves."""
    model, batches = fixture
    cfg = CFG.replace(ckpt_delta="off")
    state = jax.device_get(
        init_state(model, cfg, batches[0][0], batches[0][1])
    )
    mgr = CheckpointManager(tmp_path, cfg)
    info = mgr.save_latest(3, state, force=True)
    mgr.wait()
    assert info["mode"] == "full"
    assert mgr.ring_base_mngr.latest_step() is None
    mgr.close()


def test_delta_rebase_past_half_table(tmp_path):
    """When a delta would cover more than half the table (tiny vocab,
    wide corpus), the save re-bases instead of writing a bigger-than-full
    delta — the degradation path is the OLD behavior, never worse."""
    vocab = make_synthetic_glove(vocab_size=50)
    cfg = CFG.replace(vocab_size=52)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=6, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=3)
    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(6)]
    model = build_model(cfg, glove_init=vocab.vectors)
    step_fn = make_train_step(model, cfg)
    state = init_state(model, cfg, batches[0][0], batches[0][1])

    mgr = CheckpointManager(tmp_path, cfg)
    for sup, qry, lab in batches[:3]:
        state, _ = step_fn(state, sup, qry, lab)
    assert mgr.save_latest(3, state)["mode"] == "base"
    mgr.wait()
    for sup, qry, lab in batches[3:]:
        state, _ = step_fn(state, sup, qry, lab)
    # The 35-word corpus touches ~2/3 of the 52-row table: rebase.
    assert mgr.save_latest(6, state)["mode"] == "base"
    mgr.wait()
    template = jax.device_get(init_state(model, cfg, batches[0][0], batches[0][1]))
    restored, step_no = mgr.restore_latest(template)
    assert step_no == 6
    _assert_trees_equal(jax.device_get(state), restored)
    mgr.close()


def test_purge_ring_covers_base_and_delta(fixture, tmp_path):
    """The divergence guard's purge must delete base AND delta slots newer
    than the restored best, and drop the device diff base so the next
    ring save re-bases (orbax refuses re-saves at <= its latest step)."""
    model, batches = fixture
    step_fn = make_train_step(model, CFG)
    state = init_state(model, CFG, batches[0][0], batches[0][1])
    mgr = CheckpointManager(tmp_path, CFG)
    for sup, qry, lab in batches[:2]:
        state, _ = step_fn(state, sup, qry, lab)
    mgr.save(2, state, val_accuracy=0.9)  # the "best" to fall back to
    mgr.save_latest(3, state, force=True)
    mgr.wait()
    state2 = state
    for sup, qry, lab in batches[2:4]:
        state2, _ = step_fn(state2, sup, qry, lab)
    assert mgr.save_latest(5, state2, force=True)["mode"] == "delta"
    mgr.wait()

    mgr.purge_ring_newer_than(2)
    assert mgr.ring_base_mngr.latest_step() is None
    assert mgr.ring_delta_mngr.latest_step() is None
    template = jax.device_get(init_state(model, CFG, batches[0][0], batches[0][1]))
    _, step_no = mgr.restore_latest(template)
    assert step_no == 2  # only the best survives
    mgr.close()


def test_ring_save_telemetry_schema(fixture, tmp_path):
    """Trainer-integrated: a lazy run with val boundaries emits
    kind="ckpt" ring_save records that the obs_report schema gate accepts,
    and the run's ring slots restore to the returned state."""
    import sys
    from pathlib import Path

    from induction_network_on_fewrel_tpu.train.framework import FewShotTrainer
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    import obs_report

    vocab = make_synthetic_glove(vocab_size=VOCAB - 2)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=6, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    cfg = CFG.replace(val_step=4, val_iter=4)
    sampler = EpisodeSampler(ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=5)
    model = build_model(cfg, glove_init=vocab.vectors)
    run_dir = tmp_path / "run"
    trainer = FewShotTrainer(
        model, cfg, sampler, val_sampler=sampler, ckpt_dir=tmp_path / "ckpt",
        logger=MetricsLogger(out_dir=run_dir, quiet=True),
    )
    state = trainer.train(num_iters=9)
    trainer.close()

    n, errors = obs_report.check_schema(run_dir / "metrics.jsonl")
    assert not errors, errors
    recs = obs_report.load_records(run_dir / "metrics.jsonl")
    saves = [r for r in recs if r.get("kind") == "ckpt"]
    assert saves, "no ring-save telemetry emitted"
    assert {s["mode"] for s in saves} <= {"base", "delta", "full"}
    summary = obs_report.ckpt_summary(recs)
    assert summary["records"] == len(saves)

    mgr = CheckpointManager(tmp_path / "ckpt", cfg)
    template = jax.device_get(state)
    restored, step_no = mgr.restore_latest(template)
    assert step_no == 9
    _assert_trees_equal(template, restored)
    mgr.close()
