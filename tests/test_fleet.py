"""Fleet-tier tier-1 tests (ISSUE 13): rendezvous placement invariants,
router failover/shed-fairness/trace propagation, the all-or-nothing
fan-out publish, kind="fleet" telemetry, and the miniature 3-replica
drill replayed against the committed FLEET_r*.json band (the
tests/test_scenarios.py artifact discipline). The socket transport and
the 10k-tenant routing soak ride the slow lane.
"""

import glob
import json
import os
import sys
import threading
import time
from concurrent.futures import Future

import jax
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.fleet import (
    DEAD,
    DRAINING,
    UP,
    FleetControl,
    FleetPlacement,
    FleetPublishError,
    FleetRouter,
    InProcessReplica,
    ReplicaHandle,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs.chaos import ChaosRegistry, install
from induction_network_on_fewrel_tpu.obs.drift import DriftDetector
from induction_network_on_fewrel_tpu.obs.health import HealthWatchdog
from induction_network_on_fewrel_tpu.serving.batcher import (
    ExecuteError,
    Saturated,
)
from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker
from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import loadgen  # noqa: E402
import obs_report  # noqa: E402

CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, device="cpu",
)


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, 2)),
    )
    datasets = [
        make_synthetic_fewrel(
            num_relations=3, instances_per_relation=8,
            vocab_size=CFG.vocab_size - 2, seed=s,
        )
        for s in range(3)
    ]
    return tok, model, params, datasets


def _fleet(world, n_replicas=3, logger=None, breaker=None, **router_kw):
    tok, model, params, _ = world
    replicas = {
        f"r{i}": InProcessReplica(
            f"r{i}",
            InferenceEngine(
                model, params, CFG, tok, k=CFG.k, buckets=(1, 2, 4),
                logger=logger,
            ),
        )
        for i in range(n_replicas)
    }
    router = FleetRouter(replicas, logger=logger, breaker=breaker,
                         **router_kw)
    return router, FleetControl(router)


def _pools(datasets, k=CFG.k):
    return [
        [i for r in ds.rel_names for i in ds.instances[r][k:]]
        for ds in datasets
    ]


# --- placement invariants ---------------------------------------------------


def test_placement_deterministic_and_consistent():
    """Same tenant -> same live replica, across calls AND across
    placement instances (no table, no process state)."""
    tenants = [f"t{i:04d}" for i in range(500)]
    a = FleetPlacement([f"r{i}" for i in range(4)])
    b = FleetPlacement([f"r{i}" for i in range(4)])
    first = a.owners(tenants)
    assert first == b.owners(tenants)
    for t in tenants[:50]:
        assert a.place(t) == first[t] == a.place(t)
    # Balanced enough: no replica owns more than twice its fair share.
    from collections import Counter

    dist = Counter(first.values())
    assert set(dist) == {f"r{i}" for i in range(4)}
    assert max(dist.values()) <= 2 * (len(tenants) / 4)


def test_placement_add_remap_bound():
    """Adding a replica moves ~T/(R+1) tenants (test-pinned at 1.5x the
    expectation) and every moved tenant moves TO the newcomer — the
    rendezvous property: surviving pairs' scores are unchanged, so an
    owner can only change when the new replica wins."""
    tenants = [f"t{i:05d}" for i in range(1000)]
    pl = FleetPlacement([f"r{i}" for i in range(4)])
    before = pl.owners(tenants)
    pl.add_replica("r4")
    after = pl.owners(tenants)
    moved = [t for t in tenants if after[t] != before[t]]
    assert 0 < len(moved) <= 1.5 / 5 * len(tenants)
    assert all(after[t] == "r4" for t in moved)
    assert FleetPlacement.churn(before, after) == len(moved)


def test_placement_remove_moves_only_victims():
    """Removing (or killing) a replica moves exactly ITS tenants; every
    other tenant keeps its owner."""
    tenants = [f"t{i:05d}" for i in range(1000)]
    pl = FleetPlacement(["r0", "r1", "r2"])
    before = pl.owners(tenants)
    pl.set_state("r1", DEAD)
    after = pl.owners(tenants)
    for t in tenants:
        if before[t] == "r1":
            assert after[t] in ("r0", "r2")
        else:
            assert after[t] == before[t]
    # Revive restores the EXACT original map (pure function of ids).
    pl.set_state("r1", UP)
    assert pl.owners(tenants) == before


def test_placement_states_and_empty():
    pl = FleetPlacement(["r0", "r1"])
    pl.set_state("r0", DRAINING)
    assert pl.live() == ("r1",)
    assert pl.place("anyone") == "r1"
    pl.set_state("r1", DEAD)
    assert pl.place("anyone") is None
    with pytest.raises(ValueError):
        pl.set_state("nope", UP)
    with pytest.raises(ValueError):
        pl.set_state("r0", "sideways")


# --- router over stub replicas (routing mechanics at zero engine cost) ------


class _StubReplica(ReplicaHandle):
    """Transport-shaped stub: immediate verdicts stamped with the
    replica id (so routing is directly observable), optional unresolved
    futures (fleet-share accounting) and injected launch failures
    (breaker feed)."""

    def __init__(self, rid, hold=False, fail=False, dead_socket=False):
        self.replica_id = rid
        self.hold = hold
        self.fail = fail
        self.dead_socket = dead_socket
        self.held: list[Future] = []
        self.submits = 0
        self.version = 0

    def submit(self, instance, deadline_s=None, tenant="default",
               trace=None):
        self.submits += 1
        f: Future = Future()
        if self.hold:
            self.held.append(f)
        elif self.dead_socket:
            # SocketReplica resolves the pool future with the transport
            # error when the peer process dies — it never raises from
            # submit() itself.
            f.set_exception(ConnectionError("connection closed"))
        elif self.fail:
            f.set_exception(ExecuteError(tenant, retry_after_s=0.01))
        else:
            f.set_result({
                "label": "rel0", "tenant": tenant,
                "replica": self.replica_id,
                "trace_id": trace.trace_id if trace is not None else None,
            })
        return f

    def register_dataset(self, dataset, tenant, max_classes=None):
        return []

    def set_nota_threshold(self, threshold, tenant):
        pass

    def quarantine_tenant(self, tenant, reason=""):
        pass

    def unquarantine_tenant(self, tenant, reason=""):
        pass

    def drop_tenant(self, tenant):
        pass

    def prepare_publish(self, params=None, ckpt_dir=None):
        return object()

    def commit_publish(self, txn):
        self.version += 1
        return self.version

    def abort_publish(self, txn):
        pass

    @property
    def params_version(self):
        return self.version

    def stats_snapshot(self):
        return {"served": self.submits, "p50_ms": 0.0, "p99_ms": 0.0,
                "batch_occupancy": 1.0, "steady_recompiles": 0,
                "queue_depth": len(self.held)}

    def warmup(self):
        return 0

    def close(self):
        pass


def _stub_fleet(n=3, logger=None, breaker=None, **kw):
    replicas = {f"r{i}": _StubReplica(f"r{i}") for i in range(n)}
    router = FleetRouter(replicas, logger=logger, breaker=breaker, **kw)
    control = FleetControl(router)
    ds = object()
    for i in range(24):
        control.register_tenant(f"t{i:02d}", ds)
    return router, control, replicas


def test_router_routes_to_rendezvous_owner():
    router, control, replicas = _stub_fleet()
    try:
        for t, entry in router.directory.items():
            v = router.classify("q", tenant=t)
            assert v["replica"] == entry.owner == router.placement.place(t)
        assert sum(r.submits for r in replicas.values()) == 24
        with pytest.raises(ValueError):
            router.submit("q", tenant="never-registered")
    finally:
        router.close()


def test_fleet_share_shed_fairness():
    """A tenant over its fleet-wide in-flight share sheds AT THE DOOR
    (Saturated with the tenant set) while other tenants keep admitting —
    and the bound only binds once a second tenant exists."""
    replicas = {f"r{i}": _StubReplica(f"r{i}", hold=True) for i in range(2)}
    router = FleetRouter(replicas, fleet_share=0.5,
                         queue_capacity_per_replica=4)
    control = FleetControl(router)
    try:
        control.register_tenant("hog", object())
        control.register_tenant("mouse", object())
        cap = router._tenant_cap()   # 2 live * 4 * 0.5 = 4
        assert cap == 4
        hog_owner = router.directory["hog"].owner
        # The share binds only once a SECOND tenant has submitted (the
        # per-replica tenant_share discipline) — seed it first.
        router.submit("q", tenant="mouse")
        for _ in range(cap):
            router.submit("q", tenant="hog")
        with pytest.raises(Saturated) as exc:
            router.submit("q", tenant="hog")
        assert exc.value.tenant == "hog"
        # The other tenant still admits — fleet-level fairness.
        router.submit("q", tenant="mouse")
        # Draining the hog's futures frees its share.
        for f in replicas[hog_owner].held:
            if not f.done():
                f.set_result({"label": "rel0", "tenant": "hog",
                              "replica": hog_owner})
        router.submit("q", tenant="hog")
    finally:
        router.close()


def test_breaker_opens_marks_dead_and_fails_over():
    """Consecutive forwarded-launch failures open the per-replica
    breaker; the open transition marks the replica DEAD in placement
    (the health feed), its tenants fail over to degraded NOTA, and the
    watchdog latches ONE replica_dead critical (re-armed by revive)."""
    logger = MetricsLogger(None, quiet=True)
    watchdog = HealthWatchdog(logger=logger)
    logger.add_hook(watchdog.observe_record)
    breaker = CircuitBreaker(failure_threshold=3, open_s=60.0)
    replicas = {f"r{i}": _StubReplica(f"r{i}") for i in range(3)}
    router = FleetRouter(replicas, logger=logger, breaker=breaker)
    control = FleetControl(router)
    try:
        for i in range(24):
            control.register_tenant(f"t{i:02d}", object())
        tenant = "t00"
        victim = router.directory[tenant].owner
        replicas[victim].fail = True
        for _ in range(3):
            fut = router.submit("q", tenant=tenant)
            with pytest.raises(ExecuteError):
                fut.result(timeout=5.0)
        assert breaker.state(victim) == "open"
        assert router.placement.state(victim) == DEAD
        crits = [e for e in watchdog.events if e.event == "replica_dead"]
        assert len(crits) == 1
        # Failover: the tenant now resolves to a LIVE replica but is
        # still registered on the dead one -> degraded NOTA.
        v = router.classify("q", tenant=tenant)
        assert v["degraded"] and v["failover"] and v["nota"]
        assert v["label"] == "no_relation"
        # Re-placement recovers; only the victim's tenants moved.
        owners_before = {
            t: e.owner for t, e in router.directory.items()
        }
        moved = control.replace_tenants()
        assert moved == sum(
            1 for o in owners_before.values() if o == victim
        )
        v = router.classify("q", tenant=tenant)
        assert "degraded" not in v or not v.get("degraded")
        # Revive re-arms the latch.
        router.revive_replica(victim)
        assert f"replica_dead:{victim}" not in watchdog._latched
    finally:
        router.close()
        logger.close()


def test_breaker_opens_on_dead_socket_transport():
    """A dead replica PROCESS surfaces as ConnectionError on the routed
    future (SocketReplica resolves the pool future with the transport
    error — submit() itself never raises), and that must feed the
    per-replica breaker exactly like an ExecuteError: the replica goes
    DEAD and its tenants fail over to degraded NOTA instead of raw
    ConnectionErrors forever."""
    breaker = CircuitBreaker(failure_threshold=3, open_s=60.0)
    replicas = {f"r{i}": _StubReplica(f"r{i}") for i in range(3)}
    router = FleetRouter(replicas, breaker=breaker)
    control = FleetControl(router)
    try:
        for i in range(24):
            control.register_tenant(f"t{i:02d}", object())
        tenant = "t00"
        victim = router.directory[tenant].owner
        replicas[victim].dead_socket = True
        for _ in range(3):
            fut = router.submit("q", tenant=tenant)
            with pytest.raises(ConnectionError):
                fut.result(timeout=5.0)
        assert breaker.state(victim) == "open"
        assert router.placement.state(victim) == DEAD
        v = router.classify("q", tenant=tenant)
        assert v["degraded"] and v["failover"] and v["nota"]
    finally:
        router.close()


def test_breaker_half_open_probe_auto_revives():
    """After the open window a displaced tenant's request routes to the
    dead replica as the half-open RECOVERY PROBE: success closes the
    breaker, the closed transition revives the replica in placement,
    and service resumes on the original owner with no operator
    re-placement. A chaos/operator-killed replica (breaker still
    closed) never probes — its path stays revive + replace."""
    breaker = CircuitBreaker(failure_threshold=2, open_s=0.2)
    replicas = {f"r{i}": _StubReplica(f"r{i}") for i in range(3)}
    router = FleetRouter(replicas, breaker=breaker)
    control = FleetControl(router)
    try:
        for i in range(12):
            control.register_tenant(f"t{i:02d}", object())
        tenant = "t00"
        victim = router.directory[tenant].owner
        replicas[victim].fail = True
        for _ in range(2):
            with pytest.raises(ExecuteError):
                router.submit("q", tenant=tenant).result(timeout=5.0)
        assert router.placement.state(victim) == DEAD
        # Still inside the open window: degraded, no probe.
        assert router.classify("q", tenant=tenant)["degraded"]
        # Window elapses and the replica is healthy again: the next
        # request IS the probe — served by the original owner, breaker
        # closed, replica revived.
        replicas[victim].fail = False
        time.sleep(0.25)
        v = router.classify("q", tenant=tenant)
        assert v["replica"] == victim and not v.get("degraded")
        assert breaker.state(victim) == "closed"
        assert router.placement.state(victim) == UP
        # Chaos-kill (breaker untouched) never auto-probes.
        router.mark_replica_dead(victim, reason="drill")
        time.sleep(0.25)
        assert router.classify("q", tenant=tenant)["degraded"]
    finally:
        router.close()


def test_10k_tenant_placement_scale():
    """Placement at the ROADMAP scale: 10k tenants over 8 replicas —
    balanced, deterministic, and the add-remap bound holds. Pure
    hashing: this is the cheap half of the 10k soak (the traffic half
    rides the slow lane)."""
    tenants = [f"t{i:05d}" for i in range(10_000)]
    pl = FleetPlacement([f"r{i}" for i in range(8)])
    from collections import Counter

    dist = Counter(pl.owners(tenants).values())
    assert len(dist) == 8
    assert max(dist.values()) < 1.25 * 10_000 / 8
    assert min(dist.values()) > 0.75 * 10_000 / 8
    before = pl.owners(tenants)
    pl.add_replica("r8")
    moved = FleetPlacement.churn(before, pl.owners(tenants))
    assert 0 < moved <= 1.35 / 9 * 10_000


# --- engine-backed fleet behavior -------------------------------------------


def test_fanout_publish_atomicity(world):
    """One replica's injected ``publish.nan_params`` (the MIDDLE one, so
    an already-prepared replica must abort) rolls the WHOLE fleet back:
    every replica on its old params_version, every tenant snapshot
    unchanged, in-flight batches untouched — then a clean fan-out
    commits uniformly with zero recompiles."""
    _, _, params, datasets = world
    router, control = _fleet(world)
    try:
        pools = _pools(datasets)
        for i in range(6):
            control.register_tenant(f"t{i}", datasets[i % 3])
        for h in router.replicas.values():
            h.warmup()
        versions0 = {
            r: h.params_version for r, h in router.replicas.items()
        }
        snaps0 = {
            r: {t: h.engine.registry.snapshot(t).version
                for t in h.engine.registry.tenants()}
            for r, h in router.replicas.items()
        }
        futs = [
            router.submit(pools[i % 3][0], 10.0, tenant=f"t{i}")
            for i in range(6)
        ]
        install(ChaosRegistry.parse("publish.nan_params@1"))
        try:
            with pytest.raises(FleetPublishError) as exc:
                control.publish_params(params)
        finally:
            install(None)
        assert exc.value.replica == sorted(router.replicas)[1]
        assert versions0 == {
            r: h.params_version for r, h in router.replicas.items()
        }
        assert snaps0 == {
            r: {t: h.engine.registry.snapshot(t).version
                for t in h.engine.registry.tenants()}
            for r, h in router.replicas.items()
        }
        for f in futs:
            assert "label" in f.result(timeout=30.0)
        # Clean fan-out: uniform new version, zero recompiles.
        version = control.publish_params(params)
        assert {
            h.params_version for h in router.replicas.values()
        } == {version}
        assert all(
            h.stats_snapshot()["steady_recompiles"] == 0
            for h in router.replicas.values()
        )
    finally:
        router.close()


def test_fanout_commit_rearms_drift_once_abort_rearms_nothing(world):
    """Drift re-arm semantics across a fleet fan-out commit (ISSUE 14):
    every replica's detector re-arms EXACTLY once per COMMITTED publish
    (the engine's commit hook — post-publish drift is judged against
    the new normal), and an aborted fan-out re-arms NOTHING — no
    replica moved, so the old baselines are still the right comparison
    basis and must survive untouched, latches included."""
    tok, model, params, datasets = world
    drifts = {}
    replicas = {}
    for i in range(3):
        d = DriftDetector(eval_interval_s=0.0)
        drifts[f"r{i}"] = d
        replicas[f"r{i}"] = InProcessReplica(
            f"r{i}",
            InferenceEngine(model, params, CFG, tok, k=CFG.k,
                            buckets=(1, 2, 4), drift=d),
        )
    router = FleetRouter(replicas)
    control = FleetControl(router)
    BASE = {"nota_rate": (0.0, 0.0), "margin": (1.0, 0.1),
            "entropy": (0.1, 0.05)}
    try:
        for i in range(3):
            control.register_tenant(f"t{i}", datasets[i % 3])
        # Seed every replica's detector with calibration state (the
        # registrations above are quiet rearm no-ops — no state yet).
        for d in drifts.values():
            d.set_baseline("t0", BASE)
        assert all(d.rearms == 0 for d in drifts.values())
        # Aborted fan-out: the poisoned MIDDLE replica refuses at
        # prepare, every prepared txn aborts before anything moved.
        install(ChaosRegistry.parse("publish.nan_params@1"))
        try:
            with pytest.raises(FleetPublishError):
                control.publish_params(params)
        finally:
            install(None)
        assert all(d.rearms == 0 for d in drifts.values())
        assert all(d.armed("t0") for d in drifts.values())
        # Committed fan-out: exactly one re-arm per replica, baselines
        # dropped for re-capture from post-publish traffic.
        control.publish_params(params)
        assert [d.rearms for d in drifts.values()] == [1, 1, 1]
        assert not any(d.armed("t0") for d in drifts.values())
        # Exactly once PER committed publish, not once ever.
        for d in drifts.values():
            d.set_baseline("t0", BASE)
        control.publish_params(params)
        assert [d.rearms for d in drifts.values()] == [2, 2, 2]
    finally:
        router.close()


def test_replica_kill_chaos_failover_recover(world):
    """The fleet.replica_kill chaos point mid-traffic: the owning
    replica dies, its tenants serve degraded NOTA (zero drops), and
    re-placement recovers them on surviving replicas — per-tenant NOTA
    thresholds surviving the move."""
    _, _, _, datasets = world
    logger = MetricsLogger(None, quiet=True)
    router, control = _fleet(world, logger=logger)
    try:
        pools = _pools(datasets)
        for i in range(9):
            control.register_tenant(f"t{i}", datasets[i % 3])
        tenant = "t0"
        control.set_nota_threshold(tenant, 123.0)   # open-set floor:
        #                            everything verdicts NOTA — a marker
        #                            that must survive re-placement
        victim = router.directory[tenant].owner
        install(ChaosRegistry.parse(f"fleet.replica_kill@0:{victim}"))
        try:
            v = router.classify(pools[0][0], 10.0, tenant=tenant)
        finally:
            install(None)
        assert v["degraded"] and v["failover"]
        assert router.placement.state(victim) == DEAD
        moved = control.replace_tenants()
        assert moved >= 1 and not router.pending_failover()
        v = router.classify(pools[0][0], 10.0, tenant=tenant)
        assert not v.get("degraded")
        # The threshold moved with the tenant: still all-NOTA.
        assert v["nota"] and v["label"] == "no_relation"
        new_owner = router.directory[tenant].owner
        assert new_owner != victim
        assert router.replicas[new_owner].engine.registry.snapshot(
            tenant
        ).nota_threshold == 123.0
    finally:
        router.close()
        logger.close()


def test_trace_context_propagates_across_hop(world):
    """A router-minted TraceContext crosses the hop: the verdict's
    trace_id is the router's id, and the ring holds both the router's
    fleet/route span and the replica-side serve spans under that id."""
    from induction_network_on_fewrel_tpu.obs.spans import (
        SpanTracker,
        set_tracker,
    )

    _, _, _, datasets = world
    tracker = SpanTracker(capacity=512)
    prev = set_tracker(tracker)
    router, control = _fleet(world, n_replicas=2, trace_sample=1.0)
    try:
        control.register_tenant("t0", datasets[0])
        v = router.classify(_pools(datasets)[0][0], 10.0, tenant="t0")
        assert v.get("trace_id")
        spans = tracker.snapshot()
        route = [s for s in spans if s["name"] == "fleet/route"]
        assert route and route[0]["trace_id"] == v["trace_id"]
        execute = [
            s for s in spans
            if s["name"] == "serve/execute"
            and v["trace_id"] in tuple(s.get("links", ()))
        ]
        assert execute, [s["name"] for s in spans]
    finally:
        set_tracker(prev)
        router.close()


def test_fleet_telemetry_schema_and_report(world, tmp_path):
    """kind='fleet' records are schema-clean and the obs_report fleet
    section renders the per-replica table, churn, and fan-out row."""
    _, _, params, datasets = world
    logger = MetricsLogger(tmp_path, quiet=True)
    router, control = _fleet(world, n_replicas=2, logger=logger)
    try:
        for i in range(4):
            control.register_tenant(f"t{i}", datasets[i % 3])
        pools = _pools(datasets)
        for i in range(4):
            router.classify(pools[i % 3][0], 10.0, tenant=f"t{i}")
        control.publish_params(params)
        victim = router.directory["t0"].owner
        router.mark_replica_dead(victim, reason="test")
        router.classify(pools[0][0], 10.0, tenant="t0")   # degraded
        control.replace_tenants()
        router.emit_stats()
    finally:
        router.close()
        logger.close()
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [], errors
    recs = obs_report.load_records(tmp_path / "metrics.jsonl")
    fleet = obs_report.fleet_summary(recs)
    assert fleet["replicas"] == 2 and fleet["tenants"] == 4
    assert set(fleet["replica_table"]) == set(router.replicas)
    assert fleet["last_fanout"]["params_version"] == 1.0
    assert fleet["degraded_served"] >= 1
    assert fleet["replica_dead_faults"] == 1
    assert fleet["replace_events"] == 1


# --- the tier-1 regression gate (FLEET artifact band) -----------------------


def _latest_fleet_artifact() -> dict:
    paths = sorted(glob.glob(os.path.join(_REPO, "FLEET_r*.json")))
    assert paths, "no FLEET_r*.json artifact in the repo root"
    with open(paths[-1]) as f:
        return json.load(f)


def test_fleet_artifact_complete():
    """Acceptance shape: the committed soak artifact carries the
    per-replica table, placement churn vs bound, the fan-out publish
    row, the replica-kill drill, the zero-bands, and the tier1 block
    the gate below replays."""
    art = _latest_fleet_artifact()
    assert art["passed"] and art["placement_consistent"]
    assert art["tenants"] >= 1000          # the CPU-honest soak scale
    assert len(art["per_replica"]) >= 4
    for row in art["per_replica"].values():
        assert row["steady_recompiles"] == 0
        assert isinstance(row["qps"], (int, float))
    pl = art["placement"]
    assert pl["add_churn_frac"] <= pl["add_churn_bound"]
    fp = art["fanout_publish"]
    assert fp["uniform"] and fp["dropped"] == 0
    assert fp["steady_recompiles"] == 0
    assert isinstance(fp["publish_s"], (int, float))
    rk = art["replica_kill"]
    assert rk["criticals"] == 1 and rk["once_latched"]
    assert rk["recovered"] and rk["dropped_during_failover"] == 0
    assert art["zero_bands"] == {
        "dropped_during_failover": 0, "steady_recompiles": 0,
    }
    t1 = art["tier1"]
    assert {"replicas", "tenants", "seed", "add_churn_frac", "band",
            "placement_distribution", "replica_kill"} <= set(t1)


def test_fleet_tier1_regression_gate(tmp_path):
    """Replay the committed artifact's miniature 3-replica drill
    in-process: consistent placement under mixed traffic, the poisoned
    fan-out rolling back atomically and the clean one committing with
    zero recompiles and zero drops, bounded add-churn (EXACT — placement
    is a pure function of the ids), and replica-kill failover serving
    degraded NOTA then recovering after re-placement."""
    art = _latest_fleet_artifact()
    t1 = art["tier1"]
    logger = MetricsLogger(tmp_path, quiet=True)
    try:
        res = loadgen.fleet_tier1_drill(seed=int(t1["seed"]), logger=logger)
    finally:
        logger.close()
    assert res["passed"], res
    band = t1["band"]["churn_frac_abs"]
    assert abs(res["add_churn_frac"] - t1["add_churn_frac"]) <= band, (
        "placement churn moved vs the committed artifact — a placement/"
        "hash change must re-emit FLEET_r*.json (tools/loadgen.py "
        "--fleet ... --fleet_artifact)"
    )
    assert res["placement_distribution"] == t1["placement_distribution"]
    assert res["replica_kill"]["victim"] == t1["replica_kill"]["victim"]
    for key in ("degraded_verdict", "criticals", "once_latched",
                "recovered", "latch_rearmed_on_revive"):
        assert res["replica_kill"][key] == t1["replica_kill"][key], key
    assert res["steady_recompiles"] == 0
    # Telemetry from the replay is schema-clean (fleet kind included).
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [], errors


# --- slow lane: socket transport + scaled soak ------------------------------


@pytest.mark.slow
def test_socket_transport_fleet(world, tmp_path):
    """The same router/control stack over the JSON-lines socket
    transport: registration, routed traffic, typed backpressure, and a
    checkpoint fan-out publish — behind the SAME ReplicaHandle
    interface (the multi-process arm of ISSUE 13)."""
    from induction_network_on_fewrel_tpu.fleet.transport import (
        ReplicaServer,
        SocketReplica,
    )
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    tok, model, params, datasets = world
    # A real checkpoint: socket replicas publish from the shared
    # artifact store, not a wire-serialized params tree.
    state = init_state(
        model, CFG,
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, CFG.total_q)),
    )
    ckpt = str(tmp_path / "ckpt")
    mngr = CheckpointManager(ckpt, CFG, stage="off")
    try:
        mngr.save(0, state, val_accuracy=0.0)
        mngr.wait()
    finally:
        mngr.close()

    engines = [
        InferenceEngine(model, params, CFG, tok, k=CFG.k, buckets=(1, 2))
        for _ in range(2)
    ]
    servers = [ReplicaServer(e).start() for e in engines]
    clients = {}
    router = None
    try:
        clients = {
            f"r{i}": SocketReplica(f"r{i}", srv.address)
            for i, srv in enumerate(servers)
        }
        assert all(c.params_version == 0 for c in clients.values())
        router = FleetRouter(dict(clients))
        control = FleetControl(router)
        for i in range(6):
            control.register_tenant(f"t{i}", datasets[i % 3])
        for rid, c in clients.items():
            c.warmup()
        pools = _pools(datasets)
        for i in range(6):
            v = router.classify(pools[i % 3][0], 15.0, tenant=f"t{i}")
            assert v["tenant"] == f"t{i}" and "label" in v
        # Fan-out publish from the checkpoint dir: both processes'
        # registries commit the same new version.
        version = control.publish_checkpoint(ckpt)
        assert version == 1
        assert all(c.params_version == 1 for c in clients.values())
        # Typed errors cross the wire: unknown tenant on the replica.
        with pytest.raises(RuntimeError):
            clients["r0"].submit(
                pools[0][0], tenant="not-there"
            ).result(timeout=10.0)
    finally:
        if router is not None:
            router.close()       # closes the SocketReplica clients
        else:
            for c in clients.values():
                c.close()
        for srv in servers:
            srv.stop()
        for e in engines:
            e.close()


@pytest.mark.slow
def test_fleet_soak_10k_tenants(world):
    """The ROADMAP-scale control plane through the REAL loadgen path:
    10,000 tenants onboarded onto 4 replicas, mixed traffic, a fan-out
    publish under load, bounded add-churn, and the replica-kill
    failover arc — the full soak, slow lane (~1 min CPU; the committed
    FLEET_r01.json is the 1k in-session twin)."""
    import argparse

    args = argparse.Namespace(
        fleet=4, tenants=10_000, N=3, K=2, na_rate=0, buckets="1,2,4",
        queue_depth=64, tenant_share=0.5, deadline_ms=10000.0,
        batch_window_ms=2.0, serving_dp=None, device="cpu",
        concurrency=4, duration=2.5, seed=1, trace_sample=0.0,
    )
    import tempfile

    with tempfile.TemporaryDirectory(prefix="fleet_soak_") as tmp:
        ckpt = loadgen.make_synthetic_checkpoint(args, tmp)
        out = loadgen.run_fleet_soak(args, ckpt, None, None, None)
    assert out["passed"], out
    assert out["tenants"] == 10_000
    # The rendezvous bound holds at the full scale too.
    pl = out["placement"]
    assert pl["add_churn_frac"] <= pl["add_churn_bound"]
    assert out["zero_bands"] == {
        "dropped_during_failover": 0, "steady_recompiles": 0,
    }


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
