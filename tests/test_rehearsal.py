"""Real-data rehearsal (round-3 VERDICT item 3): ONE test composing every
real-format input path the framework supports, in the exact sequence
RUNBOOK.md documents for the day real corpora land:

1. GloVe ``glove.6B.50d.txt``-format vectors + FewRel-schema train/val JSON
   -> flagship CLI training on the production --token_cache path with NOTA
   episodes (--na_rate, CE loss) and checkpointing;
2. ``test.py`` restoring the best checkpoint and evaluating a held-out
   FewRel-schema test split (NOTA metrics included);
3. adversarial domain adaptation against a pubmed-schema (same FewRel
   JSON shape) unlabeled target file (--adv FILE, the live DANN path —
   --token_cache excludes --adv by documented design);
4. a BERT encoder run importing REAL-FORMAT artifacts: a WordPiece
   ``vocab.txt`` and an HF-name-mapped ``.npz`` weights file
   (models/bert.load_hf_weights), then test.py from its checkpoint.

Every file is written in the real on-disk format (no synthetic fallback
path is touched); only the sizes are toy. With real corpora, swap the
paths — RUNBOOK.md names the exact commands.
"""

import json

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.cli import test_main as run_test_cli
from induction_network_on_fewrel_tpu.cli import train_main as run_train_cli

DIM = 50
N_WORDS = 40
L = 12


@pytest.fixture()
def real_format_corpus(tmp_path):
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(N_WORDS)] + ["alpha", "beta", "gamma"]

    glove = tmp_path / "glove.6B.50d.txt"
    with glove.open("w") as f:
        for w in words:
            vec = " ".join(f"{v:.5f}" for v in rng.normal(0, 0.3, DIM))
            f.write(f"{w} {vec}\n")

    def instance(trigger, r):
        toks = [words[r.integers(N_WORDS)] for _ in range(8)]
        toks[2] = trigger
        toks[0], toks[5] = "alpha", "beta"
        return {
            "tokens": toks,
            "h": ["alpha", "Q1", [[0]]],
            "t": ["beta", "Q2", [[5]]],
        }

    def split(seed, prefix="P"):
        r = np.random.default_rng(seed)
        return {
            f"{prefix}{seed}{c}": [
                instance(words[c % N_WORDS], r)
                for _ in range(8 + int(r.integers(3)))
            ]
            for c in range(4)
        }

    files = {}
    for name, seed in (("train_wiki", 1), ("val_wiki", 2), ("test_wiki", 3)):
        p = tmp_path / f"{name}.json"
        p.write_text(json.dumps(split(seed)))
        files[name] = p
    # pubmed-schema DA target: same FewRel JSON shape, disjoint "domain".
    pubmed = tmp_path / "val_pubmed.json"
    pubmed.write_text(json.dumps(split(9, prefix="pm")))
    files["pubmed"] = pubmed

    # WordPiece vocab.txt (real bert-base-uncased file format: one token
    # per line; specials first).
    vocab_txt = tmp_path / "vocab.txt"
    wp = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + words + [
        "##a", "##b", "the", "of",
    ]
    vocab_txt.write_text("\n".join(wp) + "\n")
    files["vocab_txt"] = vocab_txt

    # HF-name-mapped .npz for a 1-layer, 8-wide BERT (the real import
    # format of models/bert.load_hf_weights, toy dims).
    H, FF, V = 8, 16, len(wp)
    raw = {
        "bert.embeddings.word_embeddings.weight":
            rng.normal(size=(V, H)).astype(np.float32),
        "bert.embeddings.position_embeddings.weight":
            rng.normal(size=(512, H)).astype(np.float32),
        "bert.embeddings.token_type_embeddings.weight":
            rng.normal(size=(2, H)).astype(np.float32),
        "bert.embeddings.LayerNorm.gamma": np.ones(H, np.float32),
        "bert.embeddings.LayerNorm.beta": np.zeros(H, np.float32),
    }
    lp = "bert.encoder.layer.0."
    for n in ("query", "key", "value"):
        raw[lp + f"attention.self.{n}.weight"] = (
            rng.normal(size=(H, H)).astype(np.float32)
        )
        raw[lp + f"attention.self.{n}.bias"] = (
            rng.normal(size=H).astype(np.float32)
        )
    raw[lp + "attention.output.dense.weight"] = (
        rng.normal(size=(H, H)).astype(np.float32)
    )
    raw[lp + "attention.output.dense.bias"] = (
        rng.normal(size=H).astype(np.float32)
    )
    raw[lp + "attention.output.LayerNorm.gamma"] = np.ones(H, np.float32)
    raw[lp + "attention.output.LayerNorm.beta"] = np.zeros(H, np.float32)
    raw[lp + "intermediate.dense.weight"] = (
        rng.normal(size=(FF, H)).astype(np.float32)
    )
    raw[lp + "intermediate.dense.bias"] = rng.normal(size=FF).astype(np.float32)
    raw[lp + "output.dense.weight"] = rng.normal(size=(H, FF)).astype(np.float32)
    raw[lp + "output.dense.bias"] = rng.normal(size=H).astype(np.float32)
    raw[lp + "output.LayerNorm.gamma"] = np.ones(H, np.float32)
    raw[lp + "output.LayerNorm.beta"] = np.zeros(H, np.float32)
    npz = tmp_path / "bert_tiny_hf.npz"
    np.savez(npz, **raw)
    files["bert_npz"] = npz
    files["glove"] = glove
    files["bert_dims"] = (1, H, 2, FF, V)
    return files


@pytest.mark.slow
def test_real_data_rehearsal(real_format_corpus, tmp_path):
    f = real_format_corpus
    common = ["--device", "cpu", "--sampler", "python", "--dp", "1"]

    # --- Phase 1: flagship token-cache training with NOTA on real files.
    ckpt = tmp_path / "ckpt_flagship"
    rc = run_train_cli([
        "--encoder", "cnn", "--N", "2", "--K", "2", "--Q", "2",
        "--na_rate", "1", "--loss", "ce",
        "--batch_size", "2", "--max_length", str(L), "--hidden_size", "16",
        "--induction_dim", "8", "--ntn_slices", "4",
        "--glove", str(f["glove"]),
        "--train_file", str(f["train_wiki"]),
        "--val_file", str(f["val_wiki"]),
        "--token_cache", "--steps_per_call", "4",
        "--train_iter", "24", "--val_step", "12", "--val_iter", "8",
        "--save_ckpt", str(ckpt), *common,
    ])
    assert rc == 0
    assert (ckpt / "config.json").exists()

    # --- Phase 2: test.py restores the best ckpt, evaluates the held-out
    # test split with NOTA metrics.
    rc = run_test_cli([
        "--N", "2", "--K", "2", "--Q", "2", "--na_rate", "1",
        "--batch_size", "2", "--glove", str(f["glove"]),
        "--test_file", str(f["test_wiki"]),
        "--load_ckpt", str(ckpt), "--test_iter", "8", *common,
    ])
    assert rc == 0

    # --- Phase 3: adversarial DA against the pubmed-schema target file
    # (live path: --token_cache excludes --adv by design).
    ckpt_adv = tmp_path / "ckpt_adv"
    rc = run_train_cli([
        "--encoder", "cnn", "--N", "2", "--K", "2", "--Q", "2",
        "--batch_size", "2", "--max_length", str(L), "--hidden_size", "16",
        "--induction_dim", "8", "--ntn_slices", "4",
        "--glove", str(f["glove"]),
        "--train_file", str(f["train_wiki"]),
        "--val_file", str(f["val_wiki"]),
        "--adv", str(f["pubmed"]), "--adv_batch", "4",
        "--adv_dis_hidden", "16",
        "--train_iter", "6", "--val_step", "6", "--val_iter", "4",
        "--save_ckpt", str(ckpt_adv), *common,
    ])
    assert rc == 0

    # --- Phase 4: BERT encoder with a real-format vocab.txt + HF .npz
    # weight import, then test.py from its checkpoint.
    layers, H, heads, FF, V = f["bert_dims"]
    ckpt_bert = tmp_path / "ckpt_bert"
    bert_flags = [
        "--encoder", "bert", "--bert_layers", str(layers),
        "--bert_hidden", str(H), "--bert_heads", str(heads),
        "--bert_intermediate", str(FF),
        "--bert_vocab", str(f["vocab_txt"]),
        "--bert_vocab_size", str(V),
        "--bert_weights", str(f["bert_npz"]),
    ]
    rc = run_train_cli([
        "--N", "2", "--K", "2", "--Q", "2", "--batch_size", "1",
        "--max_length", str(L), "--induction_dim", "8", "--ntn_slices", "4",
        *bert_flags,
        "--train_file", str(f["train_wiki"]),
        "--val_file", str(f["val_wiki"]),
        "--train_iter", "4", "--val_step", "4", "--val_iter", "2",
        "--save_ckpt", str(ckpt_bert), *common,
    ])
    assert rc == 0
    rc = run_test_cli([
        "--N", "2", "--K", "2", "--Q", "2", "--batch_size", "1",
        *bert_flags,
        "--test_file", str(f["test_wiki"]),
        "--load_ckpt", str(ckpt_bert), "--test_iter", "4", *common,
    ])
    assert rc == 0
