"""Sampler statistics (SURVEY.md §4.3): episode composition, determinism,
support/query disjointness, NOTA fraction and labeling."""

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.data import GloveTokenizer, make_synthetic_fewrel, make_synthetic_glove
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

N, K, Q, L, B = 5, 2, 3, 16, 2


@pytest.fixture(scope="module")
def sampler_args():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=10, instances_per_relation=20, vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=L)
    return ds, tok


def test_shapes(sampler_args):
    ds, tok = sampler_args
    s = EpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=B, seed=1)
    b = s.sample_batch()
    assert b.support_word.shape == (B, N, K, L)
    assert b.support_mask.shape == (B, N, K, L)
    assert b.query_word.shape == (B, N * Q, L)
    assert b.label.shape == (B, N * Q)
    assert b.label.dtype == np.int32
    # every class appears exactly Q times among queries
    for e in range(B):
        counts = np.bincount(b.label[e], minlength=N)
        assert (counts == Q).all()


def test_determinism(sampler_args):
    ds, tok = sampler_args
    b1 = EpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=B, seed=7).sample_batch()
    b2 = EpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=B, seed=7).sample_batch()
    for a, c in zip(b1, b2):
        np.testing.assert_array_equal(a, c)
    b3 = EpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=B, seed=8).sample_batch()
    assert any((a != c).any() for a, c in zip(b1, b3))


def test_support_query_disjoint(sampler_args):
    ds, tok = sampler_args
    s = EpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=1, seed=3)
    b = s.sample_batch()
    sup = {tuple(row) for row in b.support_word[0].reshape(-1, L)}
    qry = {tuple(row) for row in b.query_word[0]}
    # trigger-word sentences are all distinct with overwhelming probability
    assert not sup & qry


def test_nota(sampler_args):
    ds, tok = sampler_args
    na_rate = 2
    s = EpisodeSampler(ds, tok, n=N, k=K, q=Q, batch_size=4, na_rate=na_rate, seed=5)
    b = s.sample_batch()
    tq = N * Q + na_rate * Q
    assert b.query_word.shape == (4, tq, L)
    assert b.label.shape == (4, tq)
    for e in range(4):
        counts = np.bincount(b.label[e], minlength=N + 1)
        assert (counts[:N] == Q).all()
        assert counts[N] == na_rate * Q  # NOTA labeled N
    assert s.total_q == tq


def test_needs_enough_relations(sampler_args):
    ds, tok = sampler_args
    with pytest.raises(ValueError):
        EpisodeSampler(ds, tok, n=11, k=K, q=Q)
