"""Aux subsystems: checkify sanitizer, finite assertion, profiling timer."""

import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.utils.debug import assert_all_finite, checkify_step
from induction_network_on_fewrel_tpu.utils.profiling import timed_call


def test_checkify_catches_nan():
    def bad_step(x):
        return jnp.log(x)  # NaN for negative input

    checked = checkify_step(bad_step)
    out = checked(jnp.asarray(4.0))
    np.testing.assert_allclose(float(out), np.log(4.0), rtol=1e-6)
    with pytest.raises(Exception, match="nan"):
        checked(jnp.asarray(-1.0))


def test_assert_all_finite():
    assert_all_finite({"loss": jnp.asarray(1.0)})
    with pytest.raises(FloatingPointError, match="loss"):
        assert_all_finite({"loss": jnp.asarray(float("nan"))}, step=7)


def test_timed_call():
    out, dt = timed_call(lambda: (jnp.ones((64, 64)) @ jnp.ones((64, 64))).sum())
    np.testing.assert_allclose(float(out), 64.0 * 64 * 64)
    assert dt > 0.0
