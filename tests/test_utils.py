"""Aux subsystems: checkify sanitizer, finite assertion, profiling timer,
metrics logging (jsonl + TensorBoard mirror)."""

import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.utils.debug import assert_all_finite, checkify_step
from induction_network_on_fewrel_tpu.utils.profiling import timed_call


@pytest.mark.slow  # tensorflow import dominates (~6 s, only on this path)
def test_metrics_logger_tensorboard_mirror(tmp_path):
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    logger = MetricsLogger(
        out_dir=tmp_path, quiet=True, tensorboard_dir=tmp_path / "tb"
    )
    logger.log(10, "train", loss=0.5, accuracy=0.9)
    logger.log(20, "val", accuracy=0.8)
    # jsonl record is always on
    lines = (tmp_path / "metrics.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2
    # TB event files exist and contain our scalar tags
    events = list((tmp_path / "tb").glob("events.out.tfevents.*"))
    assert events, "no TensorBoard event file written"
    data = events[0].read_bytes()
    assert b"train/loss" in data and b"val/accuracy" in data


def test_checkify_catches_nan():
    def bad_step(x):
        return jnp.log(x)  # NaN for negative input

    checked = checkify_step(bad_step)
    out = checked(jnp.asarray(4.0))
    np.testing.assert_allclose(float(out), np.log(4.0), rtol=1e-6)
    with pytest.raises(Exception, match="nan"):
        checked(jnp.asarray(-1.0))


def test_assert_all_finite():
    assert_all_finite({"loss": jnp.asarray(1.0)})
    with pytest.raises(FloatingPointError, match="loss"):
        assert_all_finite({"loss": jnp.asarray(float("nan"))}, step=7)


def test_timed_call():
    out, dt = timed_call(lambda: (jnp.ones((64, 64)) @ jnp.ones((64, 64))).sum())
    np.testing.assert_allclose(float(out), 64.0 * 64 * 64)
    assert dt > 0.0


def test_train_step_flops_covers_the_zoo():
    """utils/flops.train_step_flops (round-3 VERDICT item 5) prices every
    encoder and zoo model; frozen/cached multipliers order correctly and
    the flagship wrapper delegates to the same accounting."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.utils.flops import (
        bilstm_induction_train_flops,
        train_step_flops,
    )

    base = dict(n=5, k=5, q=5, batch_size=4, max_length=40, vocab_size=2002)
    for model in ("induction", "proto", "proto_hatt", "siamese", "gnn",
                  "snail", "metanet"):
        cfg = ExperimentConfig(encoder="cnn", model=model, **base)
        f = train_step_flops(cfg)
        assert f["train"] > 0
        assert f["per_episode"] * cfg.batch_size == f["train"]
        # Implementation-overhead matmuls (one-hot select/reconstruct) are
        # tracked OUTSIDE the algorithmic fields; only gnn has any.
        assert f["overhead_flops"] >= 0
        assert (f["overhead_flops"] > 0) == (model == "gnn")
    # Above the gnn module's one_hot_max_t the broadcast fallback runs: no
    # one-hot matmuls exist (overhead 0) and the edge MLP prices T^2 pairs.
    big = train_step_flops(
        ExperimentConfig(encoder="cnn", model="gnn",
                         **{**base, "n": 13, "k": 5, "train_n": 13})
    )  # T = 66 > 64
    assert big["overhead_flops"] == 0.0
    assert big["train"] > 0
    for enc in ("cnn", "bilstm", "transformer", "bert"):
        assert train_step_flops(
            ExperimentConfig(encoder=enc, **base)
        )["train"] > 0
    bert = train_step_flops(
        ExperimentConfig(encoder="bert", bert_frozen=False, **base)
    )
    frozen = train_step_flops(
        ExperimentConfig(encoder="bert", bert_frozen=True, **base)
    )
    cached = train_step_flops(
        ExperimentConfig(encoder="bert", bert_frozen=True,
                         feature_cache=True, **base)
    )
    assert bert["train"] > frozen["train"] > cached["train"] > 0
    pair = train_step_flops(
        ExperimentConfig(encoder="bert", model="pair",
                         **{**base, "batch_size": 1})
    )
    assert pair["per_episode"] > bert["per_episode"]  # N*K*TQ pair fwds
    flag = ExperimentConfig(encoder="bilstm", **base)
    assert bilstm_induction_train_flops(flag) == train_step_flops(flag)
