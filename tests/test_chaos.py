"""Fault-domain containment (ISSUE 12): chaos registry semantics, the
per-tenant circuit breaker, typed execute-failure containment,
transactional publish rollback, degraded-mode verdict routing, and the
tier-1 miniature chaos drill (inject -> contain -> recover, in-process).

Checkpoint-side containment (quarantine + ring-walk fallback) is pinned
in tests/test_ckpt_integrity.py.
"""

import threading
import time

import jax
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs.chaos import (
    ChaosRegistry,
    chaos_active,
    chaos_fire,
    install,
)
from induction_network_on_fewrel_tpu.obs.health import HealthWatchdog
from induction_network_on_fewrel_tpu.serving.batcher import (
    ExecuteError,
    Saturated,
)
from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker
from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
from induction_network_on_fewrel_tpu.serving.engine import (
    NO_RELATION,
    InferenceEngine,
)
from induction_network_on_fewrel_tpu.serving.registry import PublishError
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, device="cpu",
)


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, 2)),
    )
    ds_a = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=8,
        vocab_size=CFG.vocab_size - 2, seed=1,
    )
    ds_b = make_synthetic_fewrel(
        num_relations=3, instances_per_relation=8,
        vocab_size=CFG.vocab_size - 2, seed=2,
    )
    return tok, model, params, ds_a, ds_b


@pytest.fixture(autouse=True)
def _no_chaos_leak():
    yield
    install(None)   # a failing test must not leak its plan into the next


def _engine(world, **kw):
    tok, model, params, _, _ = world
    return InferenceEngine(
        model, params, CFG, tok, k=CFG.k,
        buckets=kw.pop("buckets", (1, 2, 4)), start=kw.pop("start", True),
        **kw,
    )


# --- chaos registry ---------------------------------------------------------


def test_chaos_parse_and_deterministic_firing():
    reg = ChaosRegistry.parse(
        "serve.execute_raise@1*2:acme,publish.nan_params@0"
    )
    # Arrivals for the WRONG tenant don't count against the filter.
    assert reg.fire("serve.execute_raise", tenant="other") is None
    # acme arrivals: index 0 (no fire), 1 and 2 (fire), 3 (exhausted).
    assert reg.fire("serve.execute_raise", tenant="acme") is None
    assert reg.fire("serve.execute_raise", tenant="acme") is not None
    assert reg.fire("serve.execute_raise", tenant="acme") is not None
    assert reg.fire("serve.execute_raise", tenant="acme") is None
    assert reg.fire("publish.nan_params") is not None
    assert reg.fire("publish.nan_params") is None
    assert len(reg.fired_log) == 3
    # Determinism: a fresh registry over the same arrival sequence fires
    # identically.
    reg2 = ChaosRegistry.parse(
        "serve.execute_raise@1*2:acme,publish.nan_params@0"
    )
    seq = [
        reg2.fire("serve.execute_raise", tenant="other") is not None,
        reg2.fire("serve.execute_raise", tenant="acme") is not None,
        reg2.fire("serve.execute_raise", tenant="acme") is not None,
        reg2.fire("serve.execute_raise", tenant="acme") is not None,
        reg2.fire("serve.execute_raise", tenant="acme") is not None,
    ]
    assert seq == [False, False, True, True, False]


def test_chaos_two_directives_same_point_count_every_arrival(tmp_path):
    """AT is the arrival index AT THE POINT: an earlier directive firing
    must not make a later one miscount (review finding) — and a fired
    ckpt-point record with a logger attached emits cleanly, re-keying
    the ring-kind context as ckpt_kind (the record's own ``kind`` field
    is the telemetry kind; review finding)."""
    logger = MetricsLogger(tmp_path, quiet=True)
    reg = ChaosRegistry.parse(
        "ckpt.bitflip@0:ring_delta,ckpt.bitflip@2:ring_delta",
        logger=logger,
    )
    fired = [
        reg.fire("ckpt.bitflip", kind="ring_delta", step=i) is not None
        for i in range(4)
    ]
    logger.close()
    # Arrivals 0 and 2 fire — NOT 0 and 3.
    assert fired == [True, False, True, False]
    import json

    recs = [
        json.loads(line) for line in open(tmp_path / "metrics.jsonl")
    ]
    assert [r["kind"] for r in recs] == ["fault", "fault"]
    assert all(r["ckpt_kind"] == "ring_delta" for r in recs)


def test_chaos_off_is_free_and_bad_specs_raise():
    assert ChaosRegistry.parse("") is None
    assert ChaosRegistry.parse(None) is None
    with pytest.raises(ValueError, match="unknown chaos point"):
        ChaosRegistry.parse("serve.exeucte_raise@0")
    with pytest.raises(ValueError, match="lacks '@AT'"):
        ChaosRegistry.parse("serve.execute_raise")
    with pytest.raises(ValueError, match="COUNT"):
        ChaosRegistry.parse("serve.execute_raise@0*0")
    # Off = nothing installed: the fault-point call is a global check.
    install(None)
    assert not chaos_active()
    assert chaos_fire("serve.execute_raise", tenant="x") is None


# --- circuit breaker --------------------------------------------------------


def test_breaker_full_cycle_with_injected_clock():
    """closed -> open at threshold -> shed while open -> half-open probe
    (deterministic admission) -> probe FAILURE re-opens -> probe SUCCESS
    closes; every transition observed in order."""
    seen = []
    clock = [100.0]
    br = CircuitBreaker(
        failure_threshold=3, open_s=5.0, half_open_probes=1,
        clock=lambda: clock[0],
        on_transition=lambda t, f, to, n, now: seen.append((f, to)),
    )
    t = "acme"
    assert br.admit(t) is None and br.state(t) == "closed"
    br.record_failure(t)
    br.record_failure(t)
    assert br.state(t) == "closed"          # under threshold
    br.record_failure(t)
    assert br.state(t) == "open"
    retry = br.admit(t)
    assert retry is not None and 0 < retry <= 5.0   # shed with retry-after
    clock[0] += 5.1                          # past the open window
    assert br.admit(t) is None               # the probe admits
    assert br.state(t) == "half_open"
    assert br.admit(t) is not None           # only ONE probe admits
    br.record_failure(t)                     # probe failed -> re-open
    assert br.state(t) == "open"
    assert br.admit(t) is not None
    clock[0] += 5.1
    assert br.admit(t) is None               # second probe
    br.record_success(t)                     # probe succeeded -> closed
    assert br.state(t) == "closed"
    assert br.admit(t) is None
    # A success resets the failure streak: 2 failures + success + 2 more
    # never opens.
    br.record_failure(t)
    br.record_failure(t)
    br.record_success(t)
    br.record_failure(t)
    br.record_failure(t)
    assert br.state(t) == "closed"
    assert seen == [
        ("closed", "open"), ("open", "half_open"), ("half_open", "open"),
        ("open", "half_open"), ("half_open", "closed"),
    ]


def test_breaker_tenant_isolation():
    br = CircuitBreaker(failure_threshold=1, open_s=5.0)
    br.record_failure("bad")
    assert br.state("bad") == "open"
    assert br.state("good") == "closed"
    assert br.admit("good") is None


# --- execute containment ----------------------------------------------------


def test_execute_failure_contained_typed_and_worker_survives(world):
    """An injected launch failure fails ONLY its batch's futures with a
    typed ExecuteError (retry-after + cause), feeds the breaker, and the
    worker keeps serving the next query."""
    _, _, _, ds_a, _ = world
    breaker = CircuitBreaker(failure_threshold=5, open_s=1.0)
    eng = _engine(world, breaker=breaker)
    ChaosRegistry.parse("serve.execute_raise@0:acme").install()
    try:
        eng.register_dataset(ds_a, tenant="acme")
        eng.warmup()
        inst = ds_a.instances[ds_a.rel_names[0]][-1]
        with pytest.raises(ExecuteError) as ei:
            eng.classify(inst, tenant="acme")
        assert ei.value.tenant == "acme"
        assert ei.value.retry_after_s > 0
        assert "ChaosError" in str(ei.value)
        # Worker survived; the fault plan is exhausted -> next serves.
        v = eng.classify(inst, tenant="acme")
        assert v["label"] in ds_a.rel_names or v["label"] == NO_RELATION
        snap = eng.stats.snapshot()
        assert snap["execute_errors"] == 1
        assert snap["steady_recompiles"] == 0
    finally:
        eng.close()


# --- transactional publish --------------------------------------------------


def test_publish_rollback_storm_pins_old_generation(world):
    """A poisoned publish under concurrent traffic: PublishError raised,
    registry generation + every tenant snapshot unchanged, ZERO dropped
    in-flight requests, ZERO recompiles — and the next clean publish
    commits (the recovery path is intact)."""
    _, _, _, ds_a, ds_b = world
    eng = _engine(world)
    ChaosRegistry.parse("publish.nan_params@0").install()
    try:
        eng.register_dataset(ds_a, tenant="acme")
        eng.register_dataset(ds_b, tenant="globex")
        eng.warmup()
        pv0 = eng.registry.params_version
        snaps0 = {
            t: eng.registry.snapshot(t).version
            for t in eng.registry.tenants()
        }
        pools = {
            "acme": list(ds_a.instances[ds_a.rel_names[0]][CFG.k:]),
            "globex": list(ds_b.instances[ds_b.rel_names[0]][CFG.k:]),
        }
        stop = threading.Event()
        dropped = [0]
        served = [0]

        def load(tenant):
            i = 0
            while not stop.is_set():
                try:
                    eng.classify(pools[tenant][i % len(pools[tenant])],
                                 tenant=tenant)
                    served[0] += 1
                except Exception:  # noqa: BLE001 — any failure is a drop
                    dropped[0] += 1
                i += 1

        threads = [
            threading.Thread(target=load, args=(t,))
            for t in ("acme", "globex")
        ]
        for th in threads:
            th.start()
        try:
            with pytest.raises(PublishError, match="non-finite params"):
                eng.publish_params(eng.params)
        finally:
            time.sleep(0.1)
            stop.set()
            for th in threads:
                th.join(timeout=10.0)
        assert eng.registry.params_version == pv0
        assert snaps0 == {
            t: eng.registry.snapshot(t).version
            for t in eng.registry.tenants()
        }
        assert dropped[0] == 0 and served[0] > 0
        assert eng.stats.snapshot()["steady_recompiles"] == 0
        # Recovery: the chaos directive is exhausted; a clean publish
        # commits and bumps the generation.
        assert eng.publish_params(eng.params) == pv0 + 1
        assert all(
            eng.registry.snapshot(t).params_version == pv0 + 1
            for t in eng.registry.tenants()
        )
    finally:
        eng.close()


def test_publish_distill_raise_rolls_back_registry(world):
    """A failure mid-distill (after device work started) still rolls back
    completely: pool + digest index + tenants untouched."""
    tok, model, params, ds_a, _ = world
    from induction_network_on_fewrel_tpu.serving.registry import (
        TenantRegistry,
    )

    reg = TenantRegistry(model, params, tok, k=CFG.k)
    reg.register_dataset(ds_a, tenant="acme")
    pool0 = reg.pool_size()
    digests0 = set(reg._by_digest)
    ChaosRegistry.parse("publish.distill_raise@0").install()
    with pytest.raises(PublishError, match="ChaosError"):
        reg.publish_params(params)
    assert reg.params_version == 0
    assert reg.pool_size() == pool0
    assert set(reg._by_digest) == digests0
    install(None)
    assert reg.publish_params(params) == 1


def test_publish_canary_vetoes(world):
    """The optional pre-swap canary (scenario-harness floor slot): a
    raising canary rolls the publish back like any validation failure."""
    tok, model, params, ds_a, _ = world
    from induction_network_on_fewrel_tpu.serving.registry import (
        TenantRegistry,
    )

    reg = TenantRegistry(model, params, tok, k=CFG.k)
    reg.register_dataset(ds_a, tenant="acme")

    def canary(p):
        raise ValueError("quality floor breached")

    reg.publish_canary = canary
    with pytest.raises(PublishError, match="quality floor"):
        reg.publish_params(params)
    assert reg.params_version == 0
    reg.publish_canary = None
    assert reg.publish_params(params) == 1


# --- degraded mode ----------------------------------------------------------


def test_degraded_verdict_routing(world):
    """A quarantined tenant serves open-set-floor NOTA verdicts flagged
    degraded=True (zero device time); other tenants are untouched;
    unquarantine restores normal verdicts."""
    _, _, _, ds_a, ds_b = world
    eng = _engine(world)
    try:
        eng.register_dataset(ds_a, tenant="acme")
        eng.register_dataset(ds_b, tenant="globex")
        eng.warmup()
        inst_a = ds_a.instances[ds_a.rel_names[0]][-1]
        inst_b = ds_b.instances[ds_b.rel_names[0]][-1]
        batches_before = eng.stats.snapshot()["batches"]
        eng.quarantine_tenant("acme", reason="drill")
        v = eng.classify(inst_a, tenant="acme")
        assert v["label"] == NO_RELATION and v["nota"] is True
        assert v["degraded"] is True and v["logits"] == {}
        # Zero device time: no batch executed for the degraded verdict.
        assert eng.stats.snapshot()["batches"] == batches_before
        assert eng.stats.snapshot()["degraded"] == 1
        vb = eng.classify(inst_b, tenant="globex")
        assert "degraded" not in vb
        eng.unquarantine_tenant("acme")
        v2 = eng.classify(inst_a, tenant="acme")
        assert "degraded" not in v2
        assert eng.stats.snapshot()["steady_recompiles"] == 0
        # A successful publish also clears a quarantine (committed
        # generations re-validate every vector).
        eng.quarantine_tenant("acme", reason="again")
        eng.publish_params(eng.params)
        v3 = eng.classify(inst_a, tenant="acme")
        assert "degraded" not in v3
        assert eng.stats.snapshot()["steady_recompiles"] == 0
    finally:
        eng.close()


def test_degraded_probe_does_not_wedge_breaker(world):
    """A half-open probe routed to the DEGRADED path must still report an
    outcome to the breaker (review finding): without it the probe is
    silently consumed, the breaker wedges in half_open, and the tenant
    sheds forever even after unquarantine."""
    _, _, _, ds_a, _ = world
    breaker = CircuitBreaker(failure_threshold=1, open_s=0.2)
    eng = _engine(world, breaker=breaker)
    ChaosRegistry.parse("serve.execute_raise@0:acme").install()
    try:
        eng.register_dataset(ds_a, tenant="acme")
        eng.warmup()
        inst = ds_a.instances[ds_a.rel_names[0]][-1]
        with pytest.raises(ExecuteError):
            eng.classify(inst, tenant="acme")   # opens at threshold 1
        assert breaker.state("acme") == "open"
        eng.quarantine_tenant("acme", reason="drill")
        time.sleep(0.25)
        v = eng.classify(inst, tenant="acme")   # the half-open probe
        assert v["degraded"] is True
        assert breaker.state("acme") == "closed"   # NOT wedged half-open
        # Flow continues: unquarantine -> normal serving, no shed.
        eng.unquarantine_tenant("acme")
        assert "degraded" not in eng.classify(inst, tenant="acme")
    finally:
        eng.close()


# --- the tier-1 miniature chaos drill ---------------------------------------


def test_miniature_chaos_drill_inject_contain_recover(world, tmp_path):
    """The in-process replay of tools/loadgen.py --chaos_drill's serving
    half: injected execute faults trip the breaker ONCE (latched) while
    the other tenant keeps serving; a poisoned publish rolls back with
    zero drops/recompiles; recovery (probe + clean publish) re-arms the
    breaker_open and publish_rollback latches; the emitted fault stream
    passes obs_report's schema gate and renders a faults section."""
    import sys
    from pathlib import Path as _P

    sys.path.insert(0, str(_P(__file__).resolve().parent.parent / "tools"))
    import obs_report

    _, _, _, ds_a, ds_b = world
    logger = MetricsLogger(tmp_path, quiet=True)
    watchdog = HealthWatchdog(logger=logger)
    logger.add_hook(watchdog.observe_record)
    THRESHOLD, OPEN_S = 2, 0.25
    ChaosRegistry.parse(
        f"serve.execute_raise@0*{THRESHOLD}:acme,publish.nan_params@0",
        logger=logger,
    ).install()
    breaker = CircuitBreaker(failure_threshold=THRESHOLD, open_s=OPEN_S)
    eng = _engine(world, logger=logger, breaker=breaker)
    try:
        eng.register_dataset(ds_a, tenant="acme")
        eng.register_dataset(ds_b, tenant="globex")
        eng.warmup()
        inst_a = ds_a.instances[ds_a.rel_names[0]][-1]
        inst_b = ds_b.instances[ds_b.rel_names[0]][-1]

        # Inject: the breaker opens after THRESHOLD typed failures and
        # sheds from then on — once-latched CRITICAL.
        outcomes = []
        for _ in range(THRESHOLD + 3):
            try:
                eng.classify(inst_a, tenant="acme")
                outcomes.append("served")
            except ExecuteError:
                outcomes.append("exec_error")
            except Saturated:
                outcomes.append("shed")
        assert outcomes == ["exec_error"] * THRESHOLD + ["shed"] * 3
        assert breaker.state("acme") == "open"
        # The transition record is logged on the WORKER thread after the
        # client's future already resolved — wait (bounded) for it
        # rather than racing the worker's emit.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not any(
            e.event == "breaker_open" for e in watchdog.events
        ):
            time.sleep(0.01)
        assert [e.event for e in watchdog.events].count("breaker_open") == 1
        assert "label" in eng.classify(inst_b, tenant="globex")

        # Contain: poisoned publish rolls back; nothing drops.
        pv0 = eng.registry.params_version
        futs = [eng.submit(inst_b, tenant="globex") for _ in range(8)]
        with pytest.raises(PublishError):
            eng.publish_params(eng.params)
        assert all(f.result(timeout=30.0)["label"] for f in futs)
        assert eng.registry.params_version == pv0
        assert [e.event for e in watchdog.events].count(
            "publish_rollback") == 1
        # Once-latched: a second poisoned publish would re-fire only
        # after a committed one — simulate via the latch directly.
        assert "publish_rollback" in watchdog._latched

        # Recover: the probe closes the breaker; the clean publish
        # commits and re-arms the rollback latch.
        time.sleep(OPEN_S + 0.05)
        assert "label" in eng.classify(inst_a, tenant="acme")
        assert breaker.state("acme") == "closed"
        assert eng.publish_params(eng.params) == pv0 + 1
        assert "publish_rollback" not in watchdog._latched
        snap = eng.stats.snapshot()
        assert snap["steady_recompiles"] == 0
        assert snap["execute_errors"] == THRESHOLD
    finally:
        eng.close()
        logger.close()

    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert not errors, errors
    recs = obs_report.load_records(tmp_path / "metrics.jsonl")
    faults = obs_report.fault_summary(recs)
    assert faults["by_action"]["inject"] == THRESHOLD + 1
    assert faults["breaker_opens"] == 1
    assert faults["breaker_last_state"] == {"acme": "closed"}
    assert faults["publish_rollbacks"] == 1
    assert faults["execute_error_requests"] == THRESHOLD
