"""Fleet observability tier-1 tests (ISSUE 17): the NTP-style clock
offset estimator, hop-segment tiling against measured fleet latency,
fleet rollup arithmetic vs per-replica truth, the cross-process parent
chain over a real socket, timeline ordering across interleaved streams,
the fleet_report --check gate on a miniature drill, and the sampling
overhead gates (rate 0 emits nothing; the sampled record tax stays
under 2% of router p50 amortized)."""

import json
import os
import sys
import time
from concurrent.futures import Future

import jax
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.fleet import (
    FleetControl,
    FleetRouter,
    InProcessReplica,
    ReplicaHandle,
)
from induction_network_on_fewrel_tpu.fleet.journal import FleetJournal
from induction_network_on_fewrel_tpu.fleet.transport import (
    ClockSync,
    ReplicaServer,
    SocketReplica,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs.spans import (
    TraceContext,
    TraceSampler,
    get_tracker,
    new_trace_id,
)
from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import fleet_report  # noqa: E402

CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, device="cpu",
)

HOP_SEGS = ("route_ms", "queue_ms", "wire_ms", "remote_ms", "respond_ms")


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, 2)),
    )
    datasets = [
        make_synthetic_fewrel(
            num_relations=3, instances_per_relation=8,
            vocab_size=CFG.vocab_size - 2, seed=s,
        )
        for s in range(3)
    ]
    return tok, model, params, datasets


def _pool(ds, k=CFG.k):
    return [i for r in ds.rel_names for i in ds.instances[r][k:]]


def _mk_engine(world, logger=None):
    tok, model, params, _ = world
    return InferenceEngine(model, params, CFG, tok, k=CFG.k,
                           buckets=(1, 2), logger=logger)


# --- clock offset estimator -------------------------------------------------


def _probe(t0: float, offset: float, leg: float = 0.004,
           serve: float = 0.002):
    """One symmetric probe quadruple with the server clock ``offset``
    seconds ahead of the client clock."""
    t1 = t0 + leg + offset          # server receive, server clock
    t2 = t1 + serve                 # server send, server clock
    t3 = t0 + leg + serve + leg     # client receive, client clock
    return t0, t1, t2, t3


@pytest.mark.parametrize("offset", [0.5, -0.5])
def test_clock_sync_recovers_skew_both_directions(offset):
    """A symmetric-path probe recovers (server − client) exactly, for a
    server ahead AND a server behind — the sign discipline every
    downstream consumer (hop offset_ms, fleet_report's timeline
    alignment) depends on."""
    cs = ClockSync()
    for i in range(5):
        sample = cs.observe(*_probe(100.0 + i, offset))
        assert sample == pytest.approx(offset, abs=1e-9)
    assert cs.offset_s() == pytest.approx(offset, abs=1e-9)
    assert cs.rtt_s() == pytest.approx(0.008, abs=1e-9)


def test_clock_sync_median_rejects_asymmetric_outlier():
    """One probe whose return leg straddled a stall skews the mean, not
    the rolling median — the estimate stays at the true offset."""
    cs = ClockSync()
    for i in range(4):
        cs.observe(*_probe(10.0 + i, 0.25))
    # Outlier: the reply leg took 400ms (asymmetric path), which biases
    # that single sample by ~-200ms.
    t0, t1, t2, _ = _probe(20.0, 0.25)
    cs.observe(t0, t1, t2, t0 + 0.004 + 0.002 + 0.4)
    assert cs.offset_s() == pytest.approx(0.25, abs=1e-9)


def test_clock_sync_window_trims():
    cs = ClockSync(window=3)
    for i in range(10):
        cs.observe(*_probe(float(i), 0.1))
    assert cs.samples == 3
    assert ClockSync().offset_s() == 0.0   # no probes yet -> 0, not NaN


# --- real-socket: handshake + stitched parent chain -------------------------


def test_socket_parent_chain_and_handshake(world, tmp_path):
    """Satellite (b) regression: over a REAL socket, the wire carries
    the full TraceContext — the replica's ``serve/submit`` span must
    parent to the ROUTER-side originating span id, not float as a
    second root. Rides the same connection: the connect-time clock
    handshake has landed its probes and reads ~0 offset in-process."""
    tok, model, params, datasets = world
    engine = _mk_engine(world)
    srv = ReplicaServer(engine).start()
    client = None
    try:
        client = SocketReplica("r0", srv.address)
        client.register_dataset(datasets[0], "t0")
        # Connect-time handshake: probes landed, same-process clocks.
        assert client._clock.samples >= 3
        assert abs(client.clock_offset_s) < 0.05
        tracker = get_tracker()
        ctx = TraceContext(new_trace_id())
        with tracker.trace(ctx):
            with tracker.span("client/request", xplane=False):
                origin_span = ctx.span_id
                assert origin_span != 0
                v = client.submit(
                    _pool(datasets[0])[0], 10.0, tenant="t0", trace=ctx,
                ).result(timeout=30.0)
        assert v["tenant"] == "t0"
        spans = [d for d in get_tracker().snapshot()
                 if d.get("trace_id") == ctx.trace_id]
        serve_spans = [d for d in spans if d["name"] == "serve/submit"]
        assert serve_spans, f"no serve/submit span stitched: {spans}"
        assert serve_spans[0]["parent_id"] == origin_span
    finally:
        if client is not None:
            client.close()
        srv.stop()
        engine.close()


# --- hop tiling -------------------------------------------------------------


def test_hop_segments_tile_router_latency(world, tmp_path):
    """The PR 8 discipline at the fleet tier: every sampled request's
    route/queue/wire/remote/respond segments come off the same monotonic
    stamps and must sum to router_ms EXACTLY (3-decimal rounding is the
    only slack), with hop_ms = router_ms − remote_ms and remote clamped
    into the observed round-trip."""
    records = []
    logger = MetricsLogger(None, quiet=True)
    logger.add_hook(records.append)
    engine = _mk_engine(world, logger=logger)
    router = FleetRouter({"r0": InProcessReplica("r0", engine)},
                         logger=logger, trace_sample=1.0)
    try:
        control = FleetControl(router)
        control.register_tenant("t0", world[3][0])
        router.replicas["r0"].warmup()
        pool = _pool(world[3][0])
        for i in range(8):
            router.classify(pool[i % len(pool)], 10.0, tenant="t0")
    finally:
        router.close()
        logger.close()
    hops = [r for r in records if r.get("kind") == "hop"]
    assert len(hops) == 8
    for h in hops:
        ssum = sum(h[k] for k in HOP_SEGS)
        assert ssum == pytest.approx(h["router_ms"], abs=0.01), h
        assert h["hop_ms"] == pytest.approx(
            h["router_ms"] - h["remote_ms"], abs=0.01)
        assert 0.0 <= h["remote_ms"] <= h["router_ms"] + 0.01
        assert all(h[k] >= 0.0 for k in HOP_SEGS)
        assert h["trace_id"] and h["replica"] == "r0"
        # In-process handle: no wire, no clock to offset.
        assert h["offset_ms"] == 0.0


def test_sample_rate_zero_emits_nothing(world):
    """Satellite (f): rate 0 is the production default and must be
    allocation-free — the sampler short-circuits to None, the router
    never stamps, no hop (and no replica trace) record exists."""
    s = TraceSampler(0.0)
    assert all(s.maybe_trace() is None for _ in range(1000))
    records = []
    logger = MetricsLogger(None, quiet=True)
    logger.add_hook(records.append)
    engine = _mk_engine(world, logger=logger)
    router = FleetRouter({"r0": InProcessReplica("r0", engine)},
                         logger=logger, trace_sample=0.0)
    try:
        control = FleetControl(router)
        control.register_tenant("t0", world[3][0])
        router.replicas["r0"].warmup()
        pool = _pool(world[3][0])
        for i in range(6):
            router.classify(pool[i % len(pool)], 10.0, tenant="t0")
        router.emit_stats()
    finally:
        router.close()
        logger.close()
    assert [r for r in records if r.get("kind") == "hop"] == []
    assert [r for r in records
            if r.get("kind") == "trace" and "total_ms" in r] == []


def test_hop_record_tax_under_gate(world, tmp_path):
    """Satellite (f) overhead gate: the hop record's emission cost —
    json-encode + crash-visible write of the 13-field record — must
    stay under 2% of the measured router p50 when amortized at a 10%
    sampling rate (the drill's ceiling for production profiles)."""
    logger = MetricsLogger(tmp_path / "gate", quiet=True)
    records = []
    logger.add_hook(records.append)
    engine = _mk_engine(world, logger=logger)
    router = FleetRouter({"r0": InProcessReplica("r0", engine)},
                         logger=logger, trace_sample=1.0)
    try:
        control = FleetControl(router)
        control.register_tenant("t0", world[3][0])
        router.replicas["r0"].warmup()
        pool = _pool(world[3][0])
        for i in range(24):
            router.classify(pool[i % len(pool)], 10.0, tenant="t0")
        hops = [r for r in records if r.get("kind") == "hop"]
        p50_ms = sorted(h["router_ms"] for h in hops)[len(hops) // 2]
        n = 300
        t0 = time.perf_counter()
        for i in range(n):
            logger.log(
                i, kind="hop", trace_id="gate-00000001", tenant="t0",
                replica="r0", route_ms=0.01, queue_ms=0.1, wire_ms=0.0,
                remote_ms=0.5, respond_ms=0.01, router_ms=0.62,
                hop_ms=0.12, offset_ms=0.0,
            )
        emit_ms = (time.perf_counter() - t0) / n * 1e3
    finally:
        router.close()
        logger.close()
    assert 0.1 * emit_ms < 0.02 * p50_ms, (
        f"hop record tax {emit_ms:.4f}ms/record "
        f"({0.1 * emit_ms:.4f}ms amortized at 10% sampling) vs "
        f"2% of router p50 {p50_ms:.3f}ms"
    )


# --- fleet rollup vs per-replica truth --------------------------------------


class _RollupStub(ReplicaHandle):
    """Immediate-verdict handle with a controllable stats snapshot —
    the rollup test needs exact arithmetic, not engine noise."""

    def __init__(self, rid):
        self.replica_id = rid
        self.served = 0

    def submit(self, instance, deadline_s=None, tenant="default",
               trace=None) -> Future:
        self.served += 1
        fut: Future = Future()
        fut.set_result({"tenant": tenant, "replica": self.replica_id,
                        "latency_ms": 0.1})
        return fut

    def register_dataset(self, dataset, tenant, max_classes=None):
        return tenant

    def has_tenant(self, tenant):
        return True

    def stats_snapshot(self):
        return {"served": float(self.served), "p50_ms": 1.0,
                "p99_ms": 2.0, "batch_occupancy": 1.0,
                "steady_recompiles": 0.0, "queue_depth": 0.0,
                "shed": 0.0, "deadline_missed": 0.0, "degraded": 0.0}

    def close(self):
        pass


def test_fleet_rollup_matches_per_replica_truth():
    """emit_stats restates each replica's OWN counters (served straight
    from the snapshot) and derives qps from the served delta over the
    emit interval: traffic between emits shows up on exactly the
    replicas that served it, an idle interval rolls up to qps=0
    everywhere, and the aggregate row counts the live fleet."""
    records = []
    logger = MetricsLogger(None, quiet=True)
    logger.add_hook(records.append)
    stubs = {f"r{i}": _RollupStub(f"r{i}") for i in range(3)}
    router = FleetRouter(dict(stubs), logger=logger)
    try:
        control = FleetControl(router)
        for i in range(9):
            control.register_tenant(f"t{i:02d}", object())
        for i in range(9):
            router.classify("q", tenant=f"t{i:02d}")
        time.sleep(0.02)
        router.emit_stats()
        rows = {r["replica"]: r for r in records
                if r.get("kind") == "fleet" and "replica" in r}
        agg = [r for r in records
               if r.get("kind") == "fleet" and "replica" not in r
               and "event" not in r][-1]
        assert set(rows) == set(stubs)
        for rid, stub in stubs.items():
            assert rows[rid]["served"] == float(stub.served)
            assert rows[rid]["routed"] == float(
                router.routed.get(rid, 0))
            # qps sign matches the interval's truth: replicas that
            # served have qps > 0, untouched replicas roll up 0.
            assert (rows[rid]["qps"] > 0) == (stub.served > 0)
            assert rows[rid]["state"] == "up"
        assert sum(stub.served for stub in stubs.values()) == 9
        assert agg["live"] == 3.0 and agg["submitted"] == 9.0
        # Second emit over an idle interval: served deltas are zero, so
        # every replica's qps must read 0 — the rollup is a RATE, not a
        # restated lifetime counter.
        records.clear()
        time.sleep(0.02)
        router.emit_stats()
        rows2 = [r for r in records
                 if r.get("kind") == "fleet" and "replica" in r]
        assert rows2 and all(r["qps"] == 0.0 for r in rows2)
    finally:
        router.close()
        logger.close()


# --- timeline ordering across interleaved streams ---------------------------


def test_timeline_orders_interleaved_journals():
    """Records from three processes, interleaved and clock-skewed: the
    timeline must order on OFFSET-CORRECTED absolute time (replica
    t_unix minus its estimated offset), keep journal ops labeled with
    their seq, and count — not guess at — records that carry no
    absolute timestamp."""
    router_recs = [
        {"kind": "fleet", "event": "journal_op", "op": "replica_add",
         "seq": 3, "t_unix": 100.0},
        {"kind": "fault", "action": "replica_dead", "replica": "rB",
         "reason": "drill", "tenants": 2, "t_unix": 101.5},
        {"kind": "fleet", "event": "journal_op", "op": "publish_commit",
         "seq": 4, "t_unix": 103.0},
    ]
    replica_recs = {
        # rA's clock runs 500ms AHEAD: its 101.4 stamp is really 100.9,
        # which must sort BEFORE the router's 101.5 fault.
        "rA": [{"kind": "health", "event": "slo_fast_burn",
                "tenant": "t0", "burn_fast": 9.0, "t_unix": 101.4}],
        # rB's clock runs 250ms BEHIND: its 102.0 stamp is really
        # 102.25 — between the fault and the publish.
        "rB": [
            {"kind": "health", "event": "queue_stuck",
             "severity": "critical", "message": "wedged",
             "t_unix": 102.0},
            # No t_unix: identity stamping off — unplaceable across
            # processes, counted rather than invented.
            {"kind": "health", "event": "slo_slow_burn", "tenant": "t1",
             "burn_fast": 2.0},
        ],
    }
    tl = fleet_report.build_timeline(
        router_recs, replica_recs, {"rA": 500.0, "rB": -250.0}
    )
    assert tl["events"] == 5 and tl["unplaced_events"] == 1
    order = [(e["src"], e["event"].split()[0]) for e in tl["raw"]]
    assert order == [
        ("router", "journal"),       # replica_add @ 100.0
        ("rA", "SLO"),               # 101.4 - 0.5 = 100.9
        ("router", "replica"),       # rB DEAD @ 101.5
        ("rB", "CRITICAL"),          # 102.0 + 0.25 = 102.25
        ("router", "journal"),       # publish_commit @ 103.0
    ], order
    assert "seq=3" in tl["raw"][0]["event"]
    assert tl["raw"][0]["t"] == 0.0   # rebased to the first event


# --- the miniature drill: fleet_report --check in tier-1 --------------------


def test_fleet_report_check_green_on_miniature_drill(world, tmp_path):
    """The fleet_report gate end-to-end on a real miniature fleet laid
    out as the multi-stream convention: every sampled hop stitches, the
    WAL cross-check agrees with the journal_op telemetry, the timeline
    places every event — --check exits 0. Then one orphaned replica
    trace is planted and the gate must go LOUD (exit 1)."""
    tok, model, params, datasets = world
    root = tmp_path / "fleet"
    loggers = []

    def mk(rid):
        lg = MetricsLogger(root / rid, quiet=True)
        lg.set_identity("serve", replica=rid)
        loggers.append(lg)
        return InProcessReplica(rid, _mk_engine(world, logger=lg))

    replicas = {rid: mk(rid) for rid in ("r01", "r02")}
    rlog = MetricsLogger(root / "router", quiet=True)
    rlog.set_identity("router")
    loggers.append(rlog)
    router = FleetRouter(dict(replicas), logger=rlog, trace_sample=1.0)
    journal = FleetJournal(root / "journal", logger=rlog)
    control = FleetControl(router, journal=journal)
    try:
        for i, t in enumerate(("t0", "t1", "t2")):
            control.register_tenant(t, datasets[i])
        for h in router.replicas.values():
            h.warmup()
        for i in range(9):
            t = f"t{i % 3}"
            router.classify(_pool(datasets[i % 3])[i % 4], 10.0,
                            tenant=t)
        control.add_replica(mk("r03"))
        control.replace_tenants()
        control.publish_params(params)
        for i in range(6):
            router.classify(_pool(datasets[i % 3])[i % 4], 10.0,
                            tenant=f"t{i % 3}")
        router.emit_stats()
    finally:
        router.close()
        for lg in loggers:
            lg.close()
    assert fleet_report.main([str(root), "--check"]) == 0

    # Plant an orphan: a replica-side request trace no hop ever named.
    with open(root / "r01" / "metrics.jsonl", "a") as f:
        f.write(json.dumps({
            "step": 999, "kind": "trace", "wall_s": 9.9,
            "trace_id": "dead-00000099", "tenant": "t0",
            "queue_ms": 0.1, "pack_ms": 0.1, "execute_ms": 0.1,
            "respond_ms": 0.1, "total_ms": 0.4,
            "proc_role": "serve", "proc_replica": "r01",
            "proc_pid": os.getpid(), "t_unix": time.time(),
        }) + "\n")
    assert fleet_report.main([str(root), "--check"]) == 1
