"""Data layer: schema round-trip, GloVe vocab, tokenizer contract."""

import json

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    load_fewrel_json,
    make_synthetic_fewrel,
    make_synthetic_glove,
)


@pytest.fixture(scope="module")
def vocab():
    return make_synthetic_glove(vocab_size=200, word_dim=50)


@pytest.fixture(scope="module")
def ds():
    return make_synthetic_fewrel(num_relations=6, instances_per_relation=12)


def test_fewrel_json_roundtrip(tmp_path, ds):
    raw = {
        rel: [
            {
                "tokens": list(i.tokens),
                "h": [i.head_name, "Q1", [list(i.head_pos)]],
                "t": [i.tail_name, "Q2", [list(i.tail_pos)]],
            }
            for i in insts
        ]
        for rel, insts in ds.instances.items()
    }
    p = tmp_path / "train_wiki.json"
    p.write_text(json.dumps(raw))
    loaded = load_fewrel_json(p)
    assert loaded.rel_names == ds.rel_names
    first = loaded.instances[loaded.rel_names[0]][0]
    orig = ds.instances[ds.rel_names[0]][0]
    assert first.tokens == orig.tokens
    assert first.head_pos == orig.head_pos


def test_glove_vocab(vocab):
    assert vocab.vocab_size == 202  # 200 + UNK + BLANK
    assert vocab.word_dim == 50
    assert vocab.lookup("w5") == 5
    assert vocab.lookup("definitely-not-a-word") == vocab.unk_id
    np.testing.assert_array_equal(vocab.vectors[vocab.blank_id], 0.0)


def test_tokenizer_shapes_and_offsets(vocab, ds):
    L = 16
    tok = GloveTokenizer(vocab, max_length=L)
    inst = ds.instances[ds.rel_names[0]][0]
    t = tok(inst)
    assert t.word.shape == (L,) and t.word.dtype == np.int32
    assert t.pos1.shape == (L,) and t.mask.shape == (L,)
    n = min(len(inst.tokens), L)
    assert t.mask.sum() == n
    # padding uses BLANK
    if n < L:
        assert (t.word[n:] == vocab.blank_id).all()
    # position offsets: value at the head token index is exactly L (offset 0)
    head = min(inst.head_pos[0], L - 1)
    assert t.pos1[head] == L
    assert (0 <= t.pos1).all() and (t.pos1 < 2 * L).all()
    assert (0 <= t.pos2).all() and (t.pos2 < 2 * L).all()


def test_tokenizer_truncation(vocab):
    from induction_network_on_fewrel_tpu.data.fewrel import Instance

    tok = GloveTokenizer(vocab, max_length=8)
    inst = Instance(tokens=tuple(f"w{i}" for i in range(30)), head_pos=(25,), tail_pos=(2,))
    t = tok(inst)
    assert t.word.shape == (8,)
    assert t.mask.sum() == 8
    # head beyond max_length clamps to the last position
    assert t.pos1[7] == 8  # offset 0 at clamped head


def test_load_glove_txt(tmp_path):
    """Stock glove.6B-style .txt ('word v1 ... vd' per line) loads directly."""
    from induction_network_on_fewrel_tpu.data.glove import load_glove

    p = tmp_path / "glove.tiny.3d.txt"
    p.write_text("the 0.1 0.2 0.3\ncat -1.0 0.5 0.25\n")
    vocab = load_glove(p)
    assert vocab.vocab_size == 4  # 2 words + UNK + BLANK
    assert vocab.word_dim == 3
    assert vocab.lookup("cat") == 1
    assert vocab.lookup("dog") == vocab.unk_id
    import numpy as np

    np.testing.assert_allclose(vocab.vectors[0], [0.1, 0.2, 0.3])
    np.testing.assert_allclose(vocab.vectors[vocab.blank_id], 0.0)


def test_load_glove_txt_multiword_tokens(tmp_path):
    """glove.840B-style lines where the token itself contains spaces parse
    by splitting the float vector from the right."""
    from induction_network_on_fewrel_tpu.data.glove import load_glove

    p = tmp_path / "glove.weird.3d.txt"
    p.write_text("the 0.1 0.2 0.3\n. . . -1.0 0.5 0.25\n")
    vocab = load_glove(p)
    assert vocab.lookup(". . .") == 1
    import numpy as np

    np.testing.assert_allclose(vocab.vectors[1], [-1.0, 0.5, 0.25])

    bad = tmp_path / "bad.txt"
    bad.write_text("the 0.1 0.2 0.3\noops 0.1 nan-ish 0.3x\n")
    import pytest

    with pytest.raises(ValueError, match="bad.txt:2"):
        load_glove(bad)
