"""obs/ telemetry spine: spans, watchdog, flight recorder, run report.

Covers the ISSUE 2 acceptance surface: span nesting + ring-buffer
eviction, watchdog triggers on injected NaN / throughput drop / queue
stall / entropy collapse, flight-recorder dump on a simulated crash, the
metrics.jsonl schema gate (tools/obs_report.py --check), and — the tier-1
end-to-end — a 5-step synthetic training run with the watchdog enabled
producing metrics + health events + a flight-recorder dump on injected
NaN, all validating with zero schema errors.
"""

import json
import os
import sys

import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs import (
    CounterRegistry,
    FlightRecorder,
    HealthWatchdog,
    SpanTracker,
)
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train import FewShotTrainer
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import obs_report  # noqa: E402

L = 16


def _tiny_cfg(**kw):
    base = dict(
        encoder="cnn", n=2, k=2, q=2, batch_size=2, max_length=L,
        vocab_size=302, compute_dtype="float32", val_step=0, lr=1e-2,
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _setup(cfg, seed=0):
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=20, vocab_size=300, seed=seed
    )
    tok = GloveTokenizer(vocab, max_length=L)
    sampler = EpisodeSampler(
        ds, tok, n=cfg.n, k=cfg.k, q=cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate, seed=seed,
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    return model, sampler


# --- spans ----------------------------------------------------------------


def test_span_nesting_and_attrs():
    t = SpanTracker(capacity=16, xplane_bridge=False)
    with t.span("outer"):
        with t.span("inner", rows=3) as attrs:
            attrs["extra"] = 1
    spans = t.snapshot()
    # Inner closes first, so it lands first in the ring.
    inner, outer = spans[0], spans[1]
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["parent"] == "outer" and outer["parent"] is None
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert inner["attrs"] == {"rows": 3, "extra": 1}
    assert inner["dur_s"] >= 0 and outer["dur_s"] >= inner["dur_s"]


def test_span_ring_eviction_keeps_newest():
    t = SpanTracker(capacity=4, xplane_bridge=False)
    for i in range(7):
        with t.span(f"s{i}"):
            pass
    assert len(t) == 4
    assert t.evicted == 3
    names = [s["name"] for s in t.snapshot()]
    assert names == ["s3", "s4", "s5", "s6"]  # oldest first, oldest 3 gone


def test_span_decorator_and_durations():
    t = SpanTracker(capacity=8, xplane_bridge=False)

    @t.wrap("probe")
    def f(x):
        return x + 1

    assert f(1) == 2 and f(2) == 3
    assert len(t.durations("probe")) == 2


# --- watchdog -------------------------------------------------------------


def test_watchdog_nan_trips_and_dumps(tmp_path):
    logger = MetricsLogger(tmp_path, quiet=True)
    recorder = FlightRecorder(out_dir=tmp_path)
    wd = HealthWatchdog(logger=logger, recorder=recorder)
    logger.add_hook(wd.observe_record)
    logger.add_hook(recorder.record_metric)

    logger.log(1, "train", loss=0.5, episodes_per_s=100.0)
    assert not wd.tripped
    logger.log(2, "train", loss=float("nan"), episodes_per_s=100.0)
    assert wd.tripped
    assert [e.event for e in wd.events] == ["non_finite"]
    # The critical event dumped the flight recorder...
    dump = tmp_path / "flight_recorder.json"
    assert dump.exists()
    payload = json.loads(dump.read_text())
    assert "non_finite" in payload["reason"]
    assert payload["events"][0]["event"] == "non_finite"
    # ...and a kind="health" record landed in metrics.jsonl.
    kinds = [
        json.loads(l)["kind"]
        for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    assert "health" in kinds
    logger.close()


def test_watchdog_throughput_regression():
    def rec(step, eps):
        return {"step": step, "kind": "train",
                "loss": 0.1, "episodes_per_s": eps}

    wd = HealthWatchdog(throughput_drop=0.5, throughput_warmup=3)
    for step, eps in enumerate([100.0, 101.0, 99.0, 100.0]):
        wd.observe_record(rec(step, eps))
    assert len(wd.events) == 0
    wd.observe_record(rec(9, 10.0))
    assert [e.event for e in wd.events] == ["throughput_regression"]
    assert not wd.tripped  # warning severity, not critical
    # A PERSISTENT slowdown is one incident, not one event per window...
    wd.observe_record(rec(10, 12.0))
    assert len(wd.events) == 1
    # ...and the regressed windows never became the new baseline: after a
    # healthy window re-arms the latch, another drop trips again.
    wd.observe_record(rec(11, 100.0))
    wd.observe_record(rec(12, 10.0))
    assert len(wd.events) == 2


def test_watchdog_entropy_collapse():
    def rec(step, h):
        return {"step": step, "kind": "train",
                "loss": 0.1, "routing_entropy": h}

    wd = HealthWatchdog(entropy_floor=0.05)
    wd.observe_record(rec(1, 1.2))
    assert len(wd.events) == 0
    wd.observe_record(rec(2, 0.01))
    assert [e.event for e in wd.events] == ["routing_collapse"]
    assert wd.tripped
    # Pinned-at-zero entropy is ONE incident (latched), re-armed by a
    # recovery above the floor.
    wd.observe_record(rec(3, 0.01))
    assert len(wd.events) == 1
    wd.observe_record(rec(4, 1.0))
    wd.observe_record(rec(5, 0.01))
    assert len(wd.events) == 2


def test_watchdog_queue_stall_injected_clock():
    wd = HealthWatchdog(queue_stall_s=5.0)
    wd.observe_queue(queue_depth=4, served=10, now=100.0)
    wd.observe_queue(queue_depth=4, served=10, now=103.0)
    assert len(wd.events) == 0      # not stalled long enough yet
    wd.observe_queue(queue_depth=4, served=10, now=106.0)
    assert [e.event for e in wd.events] == ["queue_stall"]
    assert wd.tripped
    # Progress resets the stall clock; the same stall never re-reports,
    # but a NEW stall after progress re-arms.
    wd.observe_queue(queue_depth=4, served=11, now=120.0)  # progress: reset
    wd.observe_queue(queue_depth=4, served=11, now=130.0)  # stall begins
    assert len(wd.events) == 1
    wd.observe_queue(queue_depth=4, served=11, now=140.0)  # 10s stuck
    assert len(wd.events) == 2


def test_watchdog_ignores_health_records():
    wd = HealthWatchdog()
    wd.observe_record({"step": 1, "kind": "health", "event": "x",
                       "some_metric": float("nan")})
    assert len(wd.events) == 0      # watchdog output is not watchdog input
    # ...except grad_probe measurements, which ARE checked for NaN.
    wd.observe_record({"step": 2, "kind": "health", "event": "grad_probe",
                       "grad_norm": float("inf")})
    assert [e.event for e in wd.events] == ["non_finite"]


# --- flight recorder ------------------------------------------------------


def test_flight_recorder_dump_on_simulated_crash(tmp_path):
    tracker = SpanTracker(capacity=8, xplane_bridge=False)
    rec = FlightRecorder(out_dir=tmp_path, tracker=tracker, max_metrics=3)
    for i in range(5):
        rec.record_metric({"step": i, "kind": "train", "loss": float(i)})
    with tracker.span("train/step"):
        pass
    with pytest.raises(RuntimeError, match="boom"):
        with rec.armed("train crash"):
            raise RuntimeError("boom")
    dump = tmp_path / "flight_recorder.json"
    assert dump.exists()
    payload = json.loads(dump.read_text())
    assert payload["reason"] == "train crash: RuntimeError: boom"
    # Bounded ring: only the newest 3 metric records survive.
    assert [m["step"] for m in payload["metrics"]] == [2, 3, 4]
    assert payload["spans"][0]["name"] == "train/step"
    assert rec.dump_count == 1


# --- counter registry / prometheus ----------------------------------------


def test_counter_registry_prometheus_text():
    reg = CounterRegistry(prefix="test")
    c = reg.counter("requests_total", help="total requests")
    c.inc(); c.inc(2)
    reg.gauge("queue_depth").set(7)
    reg.gauge_fn("live_value", lambda: 1.5)
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        reg.gauge("requests_total")  # type collision
    snap = reg.snapshot()
    assert snap == {"requests_total": 3.0, "queue_depth": 7.0, "live_value": 1.5}
    text = reg.to_prometheus()
    assert "# TYPE test_requests_total counter" in text
    assert "test_requests_total 3" in text
    assert "# HELP test_requests_total total requests" in text
    assert "# TYPE test_queue_depth gauge" in text
    assert "test_live_value 1.5" in text


def test_serving_stats_bind_registry():
    from induction_network_on_fewrel_tpu.serving.stats import ServingStats

    reg = CounterRegistry()
    stats = ServingStats()
    stats.bind_registry(reg)
    stats.record_done(0.010)
    stats.record_batch(rows=3, bucket=4, exec_s=0.004)
    snap = reg.snapshot()
    assert snap["serve_served"] == 1.0
    assert snap["serve_batches"] == 1.0
    assert snap["serve_batch_occupancy"] == pytest.approx(0.75)
    assert snap["serve_p50_ms"] == pytest.approx(10.0)
    # Re-binding (engine restart in one process) must not raise.
    ServingStats().bind_registry(reg)
    # Unbinding releases the callbacks (engine.close): no stale gauges.
    stats.unbind_registry()
    fresh = ServingStats()
    fresh.bind_registry(reg)
    fresh.unbind_registry()
    assert not any(k.startswith("serve_") for k in reg.snapshot())


# --- obs_report schema gate ----------------------------------------------


def test_obs_report_check_passes_valid_stream(tmp_path, capsys):
    with MetricsLogger(tmp_path, quiet=True) as logger:
        logger.log(1, "train", loss=0.5, episodes_per_s=10.0)
        logger.log(2, "val", accuracy=0.9, acc_ci95=0.01)
        logger.log(3, "serve", served=5, p50_ms=1.0)
        logger.log(3, "health", event="grad_probe", severity="info",
                   grad_cosine=0.999)
    assert obs_report.main([str(tmp_path), "--check"]) == 0
    assert "0 schema errors" in capsys.readouterr().out


def test_obs_report_check_flags_bad_stream(tmp_path, capsys):
    (tmp_path / "metrics.jsonl").write_text(
        '{"step": 1, "kind": "train", "wall_s": 0.1, "loss": 0.5}\n'
        '{"step": "two", "kind": "train", "wall_s": 0.2}\n'   # step not int
        '{"step": 3, "kind": "mystery", "wall_s": 0.3}\n'     # unknown kind
        "not json at all\n"
        '{"step": 4, "kind": "train", "wall_s": 0.4, "v": [1]}\n'  # non-scalar
    )
    assert obs_report.main([str(tmp_path), "--check"]) == 1
    err = capsys.readouterr().err
    assert "step must be an int" in err
    assert "unknown kind" in err
    assert "not JSON" in err
    assert "must be scalar" in err


def test_obs_report_missing_dir(tmp_path):
    assert obs_report.main([str(tmp_path / "nope")]) == 2


# --- end-to-end: the tier-1 telemetry gate --------------------------------


def test_e2e_five_step_run_with_watchdog(tmp_path, capsys):
    """ISSUE 2 acceptance: a 5-step synthetic run with the watchdog enabled
    produces metrics.jsonl + health events + a flight-recorder dump on
    injected NaN, and the report renders with zero schema errors."""
    # CE loss: the MSE-sigmoid dead zone can zero the gradient within a
    # few steps on this tiny fixture, which would make the probe's norms
    # degenerate instead of exercising the healthy path.
    cfg = _tiny_cfg(nan_inject_step=3, grad_probe_every=2, loss="ce")
    model, sampler = _setup(cfg)
    logger = MetricsLogger(tmp_path, quiet=True)
    recorder = FlightRecorder(out_dir=tmp_path)
    wd = HealthWatchdog(recorder=recorder)
    trainer = FewShotTrainer(
        model, cfg, sampler, logger=logger, watchdog=wd, recorder=recorder
    )
    try:
        trainer.train(num_iters=5)
    finally:
        trainer.close()

    # Telemetry artifacts: metrics, health events, flight dump.
    recs = [
        json.loads(l)
        for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    kinds = {r["kind"] for r in recs}
    assert "train" in kinds and "health" in kinds
    # The injected NaN reached the log — serialized as the STRING "nan"
    # (bare NaN tokens are not strict JSON; the stream's contract is that
    # any JSON-lines consumer parses every line)...
    assert any(
        r["kind"] == "train" and r.get("loss") == "nan" for r in recs
    )
    # ...and every line is strict JSON (no NaN/Infinity constants).
    def _reject(c):
        raise AssertionError(f"non-strict JSON constant {c!r} in stream")

    for line in (tmp_path / "metrics.jsonl").read_text().splitlines():
        json.loads(line, parse_constant=_reject)
    # ...tripped the watchdog...
    assert wd.tripped
    assert any(e.event == "non_finite" for e in wd.events)
    # ...which dumped the flight recorder.
    assert (tmp_path / "flight_recorder.json").exists()
    # Grad probe fired (every 2 steps over 5 steps => >= 2 probes), with a
    # near-1 cosine: the run config IS f32 here, so the f32 reference
    # backward must agree with itself.
    probes = [
        r for r in recs
        if r["kind"] == "health" and r.get("event") == "grad_probe"
    ]
    assert len(probes) >= 2
    assert all(p["grad_cosine"] > 0.99 for p in probes)
    assert all(np.isfinite(p["grad_norm"]) for p in probes)

    # The report gate: zero schema errors, report renders.
    assert obs_report.main([str(tmp_path), "--check"]) == 0
    assert obs_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "0 schema errors" in out
    assert "-- health --" in out
    assert "-- flight_recorder --" in out


def test_staging_sync_never_mirrors_telemetry(tmp_path):
    """Regression: the tmpfs checkpoint staging mirror must skip live
    telemetry files in BOTH directions. Seeding snapshotted metrics.jsonl
    into staging and the next drain copied the stale snapshot back over
    the live file — on --resume every record appended through the
    logger's persistent handle was lost to a replaced inode."""
    from induction_network_on_fewrel_tpu.train.checkpoint import _sync_tree

    staging, real = tmp_path / "staging", tmp_path / "real"
    (staging / "40").mkdir(parents=True)
    (staging / "40" / "weights.bin").write_text("x")
    (staging / "metrics.jsonl").write_text('{"step": 1}\n')  # stale snapshot
    real.mkdir()
    live = '{"step": 1}\n{"step": 2}\n{"step": 3}\n'
    (real / "metrics.jsonl").write_text(live)
    _sync_tree(staging, real, mirror_deletes=True)   # the drain direction
    assert (real / "40" / "weights.bin").exists()    # checkpoints drain
    assert (real / "metrics.jsonl").read_text() == live  # telemetry doesn't
    _sync_tree(real, staging, mirror_deletes=False)  # the seed direction
    assert (staging / "metrics.jsonl").read_text() == '{"step": 1}\n'


def test_metrics_logger_persistent_handle_and_close(tmp_path):
    logger = MetricsLogger(tmp_path, quiet=True)
    logger.log(1, "train", loss=1.0)
    fh = logger._fh
    logger.log(2, "train", loss=0.5)
    assert logger._fh is fh            # ONE handle across records
    logger.close()
    assert fh.closed
    logger.log(3, "train", loss=0.25)  # reopens transparently
    logger.close()
    lines = (tmp_path / "metrics.jsonl").read_text().splitlines()
    assert len(lines) == 3


def test_evaluate_reports_ci95(tmp_path):
    """±1.96·σ/√n next to mean accuracy (VERDICT weak #8)."""
    cfg = _tiny_cfg()
    model, sampler = _setup(cfg)
    trainer = FewShotTrainer(model, cfg, sampler)
    state = trainer.init_state()
    m = trainer.evaluate(
        state.params, num_episodes=16, sampler=sampler, return_metrics=True
    )
    assert 0.0 <= m["accuracy"] <= 1.0
    assert m["acc_ci95"] >= 0.0
    # n_batches = 16/2 = 8 samples; CI must match the definition exactly.
    # (Recomputed here from a second evaluate pass over the same seeded
    # sampler would drift; instead just sanity-bound it: σ of accuracies
    # in [0,1] over 8 batches gives CI <= 1.96*0.5/sqrt(8) ~ 0.35.)
    assert m["acc_ci95"] <= 0.4


# --- roofline section (ISSUE 6) -------------------------------------------


def test_roofline_record_and_report_section(tmp_path, capsys):
    """A bilstm trainer emits kind="roofline" per metric window (the
    shared step-byte arithmetic at this config's residual knobs) and
    obs_report renders the section — step_mb headline, per-component
    table rebuilt from config.json — with --check green."""
    cfg = _tiny_cfg(encoder="bilstm", lstm_hidden=8, att_dim=4,
                    induction_dim=8, ntn_slices=4)
    model, sampler = _setup(cfg)
    logger = MetricsLogger(tmp_path, quiet=True)
    trainer = FewShotTrainer(model, cfg, sampler, logger=logger)
    try:
        trainer.train(num_iters=3)
    finally:
        trainer.close()
    (tmp_path / "config.json").write_text(cfg.to_json())

    recs = [
        json.loads(l)
        for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    rl = [r for r in recs if r["kind"] == "roofline"]
    assert rl, "bilstm run emitted no kind='roofline' records"
    from induction_network_on_fewrel_tpu.utils.roofline import (
        lstm_residual_bytes,
        step_bytes,
    )

    assert rl[-1]["step_bytes"] == step_bytes(cfg)
    assert rl[-1]["lstm_residual_bytes"] == lstm_residual_bytes(cfg)
    assert rl[-1]["step_mb"] == round(step_bytes(cfg) / 1e6, 3)

    assert obs_report.main([str(tmp_path), "--check"]) == 0
    assert obs_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "roofline" in out and "step_mb" in out
    # The component table came from the shared formulas via config.json.
    assert "components_mb" in out and "bilstm kernel" in out


def test_roofline_summary_without_config_is_headline_only(tmp_path):
    """No config.json -> the section still carries the headline numbers
    (the table is best-effort)."""
    with MetricsLogger(tmp_path, quiet=True) as logger:
        logger.log(1, "roofline", step_bytes=1000.0, step_mb=0.001,
                   lstm_residual_bytes=100.0, lstm_cs_window=8.0)
    summary = obs_report.roofline_summary(
        [{"kind": "roofline", "step_mb": 0.001, "step_bytes": 1000.0,
          "lstm_residual_bytes": 100.0, "lstm_cs_window": 8.0}],
        tmp_path,
    )
    assert summary["step_mb"] == 0.001
    assert "components_mb" not in summary
    assert obs_report.main([str(tmp_path), "--check"]) == 0
