"""Tier-1 byte-regression gates (ISSUE 6 satellite): neither the HBM
step-byte arithmetic nor the wire payload can silently regress.

* STEP-BYTE GATE: ``utils/roofline.step_bytes`` at the flagship config
  must stay within +2% of the value recorded in the newest
  ``ROOFLINE_r*.json`` — a formula change (or a knob-default change) that
  inflates the modeled step shows up here before it ships, the same way
  the comms ±15% band guards the wire model. The gate reads the artifact
  so re-emitting the ledger (tools/roofline_ledger.py --json) is the ONE
  sanctioned way to move the recorded value.
* FLAGSHIP --strict GATE: ``tools/comms_ledger.py --only-flagship
  --strict`` must exit 0 — the compiled flagship-shape HLO keeps every
  collective attributed and the payload inside the ±15% band. (The FULL
  ledger still carries the documented attribution-debt legs — RUNBOOK
  §11 — which is why tier-1 pins the flagship-only run, not the suite.)
* Windowed-cs arithmetic sanity: the round-8 terms behave (windowed <
  full-cs, bf16 halves residual storage, W >= L clamps) so the headline
  drop in ROOFLINE_r08 is the formulas, not a transcription.
"""

import glob
import json
import os
import sys

import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.utils.roofline import (
    lstm_residual_bytes,
    step_bytes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The flagship ledger config — must match tools/roofline_ledger.py main().
FLAGSHIP = ExperimentConfig(
    encoder="bilstm", n=5, k=5, q=5, batch_size=64, max_length=40,
    vocab_size=400002, compute_dtype="bfloat16", steps_per_call=256,
    token_cache=True, embed_optimizer="lazy", remat_attn=True,
)


def _latest_roofline() -> dict:
    paths = sorted(glob.glob(os.path.join(REPO, "ROOFLINE_r*.json")))
    assert paths, "no ROOFLINE_r*.json artifact in the repo root"
    with open(paths[-1]) as f:
        return json.load(f)


def test_step_bytes_regression_gate():
    """step_bytes at the flagship config (production knobs: remat_attn on,
    the config-default cs window, auto residual dtype) <= the newest
    recorded round value + 2%."""
    rec = _latest_roofline()
    got = step_bytes(FLAGSHIP)
    ceiling = rec["step_bytes"] * 1.02
    assert got <= ceiling, (
        f"flagship step bytes {got} exceed the recorded "
        f"{rec['step_bytes']} + 2% ({ceiling:.0f}) — a formula or "
        "knob-default change inflated the modeled step; re-emit the "
        "ledger (tools/roofline_ledger.py --json ROOFLINE_r<next>.json) "
        "if the change is intended"
    )
    # The A/B twins recorded alongside (round-8 artifacts onward) gate the
    # policy ladder too, so a regression can't hide in a non-default leg.
    if "step_bytes_full_cs" in rec:
        full = step_bytes(FLAGSHIP, lstm_cs_window=0)
        assert full <= rec["step_bytes_full_cs"] * 1.02
    if "step_bytes_no_remat" in rec:
        no_remat = step_bytes(FLAGSHIP, remat_attn=False, lstm_cs_window=0)
        assert no_remat <= rec["step_bytes_no_remat"] * 1.02


def test_windowed_cs_arithmetic_sanity():
    """Round-8 term behavior: windowed residuals shrink monotonically-ish
    with W (1/W checkpoint traffic), bf16 halves the storage term, W >= L
    clamps to one window, and the windowed step undercuts full-cs by the
    ISSUE-6 target margin (>= 15%) at the flagship shape."""
    full = lstm_residual_bytes(FLAGSHIP, lstm_cs_window=0)
    w8 = lstm_residual_bytes(FLAGSHIP, lstm_cs_window=8)
    w1 = lstm_residual_bytes(FLAGSHIP, lstm_cs_window=1)
    assert w8 < full
    # W=1 checkpoints BOTH h and c every step — 2x the cs-only stream.
    assert w1 == 2 * full
    assert lstm_residual_bytes(FLAGSHIP, lstm_cs_window=40) == \
        lstm_residual_bytes(FLAGSHIP, lstm_cs_window=400)
    assert lstm_residual_bytes(FLAGSHIP, lstm_residuals="bf16") * 2 == \
        lstm_residual_bytes(FLAGSHIP, lstm_residuals="f32")

    step_win = step_bytes(FLAGSHIP)                      # W=8 default
    step_full = step_bytes(FLAGSHIP, lstm_cs_window=0)   # round-6/7 policy
    assert step_win <= 0.85 * step_full, (
        f"windowed step {step_win} not >=15% under full-cs {step_full} — "
        "the ISSUE-6 acceptance margin regressed"
    )


def test_comms_ledger_flagship_strict(monkeypatch, capsys):
    """tools/comms_ledger.py --only-flagship --strict exits 0: the
    compiled flagship step keeps zero unattributed collectives and the
    payload inside the ±15% band (the tier-1-automatable guard while the
    full suite carries the documented debt legs)."""
    import tools.comms_ledger as cl

    monkeypatch.setattr(
        sys, "argv", ["comms_ledger.py", "--only-flagship", "--strict"]
    )
    rc = cl.main()
    out = capsys.readouterr().out
    assert rc == 0, f"flagship strict ledger failed:\n{out}"
    # Round 21: the flagship leg runs bucketed (grad_bucketing="on"), so
    # the single-fragment demb overlap report is superseded by the
    # whole-step measure — the gradient psums must land in the named
    # buckets and the measured un-overlapped share must print (the <= 8%
    # assertion itself lives in check_flagship).
    assert "grad/bucket_" in out, (
        "the bucketed gradient psums are missing from the flagship leg"
    )
    assert "un-overlapped" in out, (
        "the measured whole-step overlap headline is missing"
    )


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
