"""Online prediction-drift detection (ISSUE 10, obs/drift.py).

Covers: baseline capture + band math, the injectable-clock evaluation
throttle, once-latched WARNING/CRITICAL with diagnostics auto-capture,
re-arm on return-to-band and on rearm() (the publish path), explicit
set_baseline (publish-time calibration), the engine-level drill in
miniature (OOV traffic shift trips a once-latched CRITICAL; a publish
re-arms; kind="quality" records pass obs_report --check), and the SLO
engine's quality-feature plumbing through ServingStats.
"""

import json
import os
import sys

import jax
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.fewrel import Instance
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.obs import (
    DiagnosticsCapture,
    DriftDetector,
    FlightRecorder,
)
from induction_network_on_fewrel_tpu.obs.drift import quality_features
from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.serving.stats import ServingStats
from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import obs_report  # noqa: E402


def _feed(det, tenant, n, nota_p, margin, entropy, t0=0.0, dt=1.0,
          rng=None):
    """n observations with an evenly spread nota pattern at rate nota_p
    (Bresenham accumulator: exact long-run rate at any window size)."""
    import math

    evs = []
    for i in range(n):
        nota = math.floor((i + 1) * nota_p) > math.floor(i * nota_p)
        evs += det.observe(tenant, nota=nota, margin=margin,
                           entropy=entropy, now=t0 + i * dt)
    return evs


def test_baseline_capture_then_quiet_on_stable_traffic():
    det = DriftDetector(window=32, baseline_n=16, min_count=8,
                        eval_interval_s=0.0)
    assert not det.armed("t")
    evs = _feed(det, "t", 16, nota_p=0.1, margin=1.0, entropy=0.5)
    assert det.armed("t") and evs == []
    base = det.baseline_for("t")
    assert abs(base["nota_rate"][0] - 0.1) < 0.05
    # Same-distribution traffic stays quiet.
    evs = _feed(det, "t", 64, nota_p=0.1, margin=1.0, entropy=0.5, t0=100)
    assert evs == [] and not det.tripped


def test_shift_trips_once_latched_critical_with_capture(tmp_path):
    rec = FlightRecorder(out_dir=tmp_path)
    det = DriftDetector(
        window=32, baseline_n=16, min_count=8, eval_interval_s=0.0,
        recorder=rec,
        capture=DiagnosticsCapture(tmp_path, recorder=rec, profile=False),
    )
    _feed(det, "t", 16, nota_p=0.0, margin=1.0, entropy=0.5)
    # Injected shift: NOTA rate 0 -> 1. Must cross the critical band
    # (floor 0.05 * crit_factor 2 = 0.1 shift) well within one window.
    evs = _feed(det, "t", 32, nota_p=1.0, margin=1.0, entropy=0.5, t0=100)
    crits = [e for e in evs if e.severity == "critical"]
    assert det.tripped and len(crits) == 1
    assert crits[0].event == "prediction_drift"
    assert crits[0].data["feature"] == "nota_rate"
    # Once-latched: continued shift emits nothing new.
    evs = _feed(det, "t", 32, nota_p=1.0, margin=1.0, entropy=0.5, t0=200)
    assert [e for e in evs if e.severity == "critical"] == []
    # Diagnostics on disk (CPU-honest: span snapshot + flight dump).
    (latch, cap), = det.captured.items()
    assert latch == "drift:t:nota_rate:critical"
    assert cap["span_snapshot"] and os.path.exists(cap["span_snapshot"])
    assert cap["flight_dump"] and os.path.exists(cap["flight_dump"])


def test_return_to_band_rearms_latch():
    det = DriftDetector(window=16, baseline_n=8, min_count=8,
                        eval_interval_s=0.0)
    _feed(det, "t", 8, nota_p=0.0, margin=1.0, entropy=0.5)
    evs = _feed(det, "t", 16, nota_p=1.0, margin=1.0, entropy=0.5, t0=100)
    assert any(e.severity == "critical" for e in evs)
    # Back inside the band: the window refills with baseline-like
    # traffic, the latch re-arms, a second excursion re-trips.
    evs = _feed(det, "t", 32, nota_p=0.0, margin=1.0, entropy=0.5, t0=200)
    assert not any(e.event == "prediction_drift" for e in evs)
    evs = _feed(det, "t", 16, nota_p=1.0, margin=1.0, entropy=0.5, t0=300)
    assert any(e.severity == "critical" for e in evs)


def test_critical_latch_holds_through_dip_to_warning():
    """Shift noise around the critical boundary is ONE incident: a dip
    from critical to merely-warning territory must not re-arm the
    critical latch (else each re-crossing fires a fresh capture). Only
    returning fully inside the band re-arms."""
    det = DriftDetector(window=20, baseline_n=8, min_count=20,
                        eval_interval_s=0.0, nota_rate_floor=0.1)
    _feed(det, "t", 8, nota_p=0.0, margin=1.0, entropy=0.5)
    # Window mean 0.25 > 2*0.1 -> CRITICAL.
    _feed(det, "t", 20, nota_p=0.25, margin=1.0, entropy=0.5, t0=100)
    # Dip to 0.15 (warning band), then back to 0.25: no second critical.
    _feed(det, "t", 20, nota_p=0.15, margin=1.0, entropy=0.5, t0=200)
    _feed(det, "t", 20, nota_p=0.25, margin=1.0, entropy=0.5, t0=300)
    crits = [e for e in det.events if e.severity == "critical"]
    assert len(crits) == 1, crits


def test_quality_snapshot_rate_over_quality_bearing_only():
    """nota_rate's denominator is the quality-BEARING verdict count:
    legacy record_done calls without quality features must not dilute
    it."""
    stats = ServingStats()
    for _ in range(50):
        stats.record_done(0.001, tenant="a")            # legacy, no quality
    for i in range(50):
        stats.record_done(0.001, tenant="a", nota=(i < 10), margin=0.5,
                          entropy=1.0)
    snap = stats.quality_snapshot()["a"]
    assert snap["served"] == 100
    assert abs(snap["nota_rate"] - 0.2) < 1e-6, snap


def test_warning_band_before_critical():
    det = DriftDetector(window=100, baseline_n=16, min_count=100,
                        eval_interval_s=0.0, nota_rate_floor=0.1)
    _feed(det, "t", 16, nota_p=0.0, margin=1.0, entropy=0.5)
    # Shift the window mean to ~0.15: past the 0.1 band, inside the 0.2
    # critical band -> WARNING only.
    evs = _feed(det, "t", 100, nota_p=0.15, margin=1.0, entropy=0.5,
                t0=100)
    drift = [e for e in evs if e.event == "prediction_drift"]
    assert drift and all(e.severity == "warning" for e in drift)
    assert not det.tripped


def test_eval_interval_throttles_with_injectable_clock():
    det = DriftDetector(window=16, baseline_n=8, min_count=8,
                        eval_interval_s=10.0)
    _feed(det, "t", 8, nota_p=0.0, margin=1.0, entropy=0.5)
    # All observations inside one eval interval: at most ONE judgment
    # runs, so at most one event despite a full-window shift.
    evs = _feed(det, "t", 16, nota_p=1.0, margin=1.0, entropy=0.5,
                t0=100, dt=0.01)
    assert len([e for e in evs if e.event == "prediction_drift"]) <= 1
    # Advancing the injected clock past the interval judges again (the
    # nota_rate latch is held, but margin is clean — no flood either).
    evs = det.observe("t", nota=True, margin=1.0, entropy=0.5, now=500.0)
    assert [e.event for e in evs] in ([], ["prediction_drift"])


def test_rearm_drops_baseline_and_recaptures():
    det = DriftDetector(window=16, baseline_n=8, min_count=8,
                        eval_interval_s=0.0)
    _feed(det, "t", 8, nota_p=0.0, margin=1.0, entropy=0.5)
    _feed(det, "t", 16, nota_p=1.0, margin=1.0, entropy=0.5, t0=100)
    assert det.tripped
    det.rearm(reason="publish v2")
    assert not det.armed("t") and det.rearms == 1
    rearms = [e for e in det.events if e.event == "drift_rearm"]
    assert len(rearms) == 1 and "publish v2" in rearms[0].message
    # Post-rearm the SHIFTED distribution becomes the new baseline —
    # steady shifted traffic is the new normal, no events.
    evs = _feed(det, "t", 40, nota_p=1.0, margin=1.0, entropy=0.5, t0=200)
    assert det.armed("t")
    assert not any(e.event == "prediction_drift" for e in evs)


def test_set_baseline_from_calibration_artifact():
    det = DriftDetector(window=16, baseline_n=8, min_count=8,
                        eval_interval_s=0.0)
    det.set_baseline("t", {
        "nota_rate": (0.1, 0.3), "margin": (1.0, 0.2),
        "entropy": (0.5, 0.1),
    })
    assert det.armed("t")     # no traffic needed
    evs = _feed(det, "t", 16, nota_p=0.1, margin=1.0, entropy=0.5)
    assert evs == []
    evs = _feed(det, "t", 16, nota_p=1.0, margin=1.0, entropy=0.5, t0=100)
    assert any(e.severity == "critical" for e in evs)
    with pytest.raises(ValueError):
        det.set_baseline("t", {"nota_rate": (0.0, 0.0)})


def test_min_count_never_exceeds_window():
    """A detector whose min_count can never be reached (window-capped
    deque) would be a silent no-op: explicit min_count > window is
    refused, and the default adapts to small windows so they are judged
    when full."""
    with pytest.raises(ValueError):
        DriftDetector(window=16, min_count=32)
    det = DriftDetector(window=16, baseline_n=8)   # default min_count
    assert det.min_count == 16
    _feed(det, "t", 8, nota_p=0.0, margin=1.0, entropy=0.5)
    evs = _feed(det, "t", 32, nota_p=1.0, margin=1.0, entropy=0.5, t0=100)
    assert any(e.severity == "critical" for e in evs)   # it judges


def test_quality_features_formula():
    m, e = quality_features(np.array([2.0, 1.0, 0.0]))
    assert abs(float(m) - 1.0) < 1e-9
    p = np.exp([2.0, 1.0, 0.0])
    p /= p.sum()
    assert abs(float(e) - float(-(p * np.log(p)).sum())) < 1e-9
    # Vectorized + n=1 degenerate.
    m2, _ = quality_features(np.zeros((4, 1)))
    assert m2.shape == (4,) and float(m2.max()) == 0.0


def test_stats_quality_snapshot_and_emit(tmp_path):
    stats = ServingStats()
    for i in range(10):
        stats.record_done(0.001, tenant="a", nota=(i < 3), margin=0.5,
                          entropy=1.2)
    snap = stats.quality_snapshot()["a"]
    assert snap["served"] == 10 and abs(snap["nota_rate"] - 0.3) < 1e-6
    assert snap["margin_p50"] == 0.5 and snap["entropy_p50"] == 1.2
    logger = MetricsLogger(tmp_path, quiet=True)
    stats.emit(logger, step=1)
    logger.close()
    lines = [json.loads(ln) for ln in
             (tmp_path / "metrics.jsonl").read_text().splitlines()]
    quality = [r for r in lines if r["kind"] == "quality"]
    assert len(quality) == 1 and quality[0]["tenant"] == "a"
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == []


# --- engine-level drill in miniature ---------------------------------------

CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, device="cpu",
)


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, 2)),
    )
    ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=10,
        vocab_size=CFG.vocab_size - 2, seed=1,
    )
    return tok, model, params, ds


def _drain(eng):
    while eng.batcher.queue_depth:
        eng.batcher.drain_once(block_s=0.01)


def test_engine_drift_drill_miniature(tmp_path, world):
    """The loadgen drift drill's logic at tier-1 scale: calibrated NOTA
    floor -> baseline -> OOV shift trips once-latched critical with
    capture -> publish re-arms -> clean re-baseline; the run's
    kind='quality' records pass obs_report --check."""
    from tools.loadgen import _nota_gap, calibrate_drift_floor

    tok, model, params, ds = world
    logger = MetricsLogger(tmp_path, quiet=True)
    det = DriftDetector(
        window=24, baseline_n=16, min_count=12, eval_interval_s=0.0,
        capture=DiagnosticsCapture(tmp_path, recorder=None, profile=False),
    )
    eng = InferenceEngine(
        model, params, CFG, tok, k=CFG.k, buckets=(1, 8),
        logger=logger, drift=det, start=False,
    )
    try:
        eng.register_dataset(ds, tenant="acme")
        eng.warmup()
        pool = [i for r in ds.rel_names for i in ds.instances[r][CFG.k:]]
        oov = Instance(tokens=tuple("zqx%d" % j for j in range(8)),
                       head_pos=(0,), tail_pos=(1,))

        def classify(inst):
            fut = eng.submit(inst, tenant="acme")
            _drain(eng)
            return fut.result(timeout=5.0)

        # Verdicts carry the quality features.
        v = classify(pool[0])
        assert {"nota", "margin", "entropy"} <= set(v)

        probe_in = [classify(p) for p in pool]
        probe_oov = [classify(oov) for _ in range(3)]
        cal = calibrate_drift_floor(
            [_nota_gap(x) for x in probe_in],
            [_nota_gap(x) for x in probe_oov],
        )
        # Deterministic calibration: the floor splits the clean pool
        # from the OOV point mass completely, and the clean pool covers
        # a real fraction of the in-domain traffic.
        assert cal["clean_idx"] and cal["clean_frac"] > 0
        clean = [pool[i] for i in cal["clean_idx"]]
        # The probe traffic armed the detector; changing the tenant's
        # threshold is a control-plane distribution change and must
        # re-arm it automatically (engine._drift_rearm).
        assert det.armed("acme")
        eng.set_nota_threshold(cal["threshold"], tenant="acme")
        assert not det.armed("acme")
        for i in range(det.baseline_n + det.min_count):
            classify(clean[i % len(clean)])
        assert det.armed("acme")
        assert det.baseline_for("acme")["nota_rate"][0] == cal["base_rate"]

        for _ in range(det.window):
            classify(oov)
            if det.tripped:
                break
        assert det.tripped, det.drift_state("acme")
        crits = [e for e in det.events if e.severity == "critical"]
        assert any(e.data.get("feature") == "nota_rate" for e in crits)
        for _ in range(det.min_count):          # once-latch
            classify(oov)
        # Once-latch is per (tenant, feature): a sustained shift emits
        # at most ONE critical per feature (margin may legitimately
        # latch after nota_rate — a second feature, not a re-fire).
        from collections import Counter

        per_feature = Counter(
            e.data.get("feature") for e in det.events
            if e.severity == "critical"
        )
        assert all(v == 1 for v in per_feature.values()), per_feature
        assert det.captured            # capture on disk
        cap = next(iter(det.captured.values()))
        assert os.path.exists(cap["span_snapshot"])

        # Publish re-arms; clean-pool traffic re-baselines quietly (the
        # NOTA rate over the clean pool is deterministic, so no
        # nota_rate event and nothing critical; margin/entropy cycling
        # warnings are a different feature and tolerated).
        eng.publish_params(eng.params)
        assert det.rearms >= 1 and not det.armed("acme")
        before = len([e for e in det.events
                      if e.event == "prediction_drift"])
        for i in range(det.baseline_n + det.min_count):
            classify(clean[i % len(clean)])
        assert det.armed("acme")
        new = [
            e for e in det.events if e.event == "prediction_drift"
        ][before:]
        assert not any(
            e.severity == "critical"
            or e.data.get("feature") == "nota_rate"
            for e in new
        ), new
        eng.emit_stats()
    finally:
        eng.close()
        logger.close()
    n, errors = obs_report.check_schema(tmp_path / "metrics.jsonl")
    assert errors == [], errors
    recs = obs_report.load_records(tmp_path / "metrics.jsonl")
    q = obs_report.quality_summary(recs)
    assert q and "acme" in q.get("tenants", {})
    assert q.get("drift_events", 0) >= 1 and q.get("rearms", 0) >= 1


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
