"""Worker process for the REAL 2-process jax.distributed hostfeed test.

Run as ``python hostfeed_worker.py <process_id> <coordinator_port>``.
Each process owns 4 virtual CPU devices; the two of them form one
8-device dp mesh via jax.distributed (Gloo over localhost). The process
samples ONLY its own episode rows (parallel/hostfeed.py), assembles
global index batches with jax.make_array_from_process_local_data, and
runs 3 mesh-sharded token-cached train steps. Emits one JSON line
{pid, loss, norm}; the spawning test asserts both processes agree —
which can only happen if the cross-process collectives and the per-host
feed composed correctly.
"""

import json
import os
import sys


def main(pid: int, port: int) -> None:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")  # before any backend init
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2,
        process_id=pid, local_device_ids=list(range(4)),
    )
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.build import (
        batch_to_model_inputs,
    )
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.hostfeed import (
        GlobalBatchAssembler,
        PerHostSampler,
        local_episode_range,
        process_seed,
    )
    from induction_network_on_fewrel_tpu.parallel.sharding import shard_state
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_train_step,
        tokenize_dataset,
    )

    assert jax.process_count() == 2
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    cfg = ExperimentConfig(
        encoder="cnn", n=3, k=2, q=2, batch_size=8, max_length=12,
        vocab_size=52, hidden_size=16, dp=8, sampler="python",
    )
    vocab = make_synthetic_glove(vocab_size=50)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=8, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    mesh = make_mesh(dp=8)

    _, local_b = local_episode_range(mesh, cfg.batch_size)
    assert local_b == cfg.batch_size // 2, local_b
    table_np, sizes = tokenize_dataset(ds, tok)
    table = jax.device_put(
        table_np, jax.tree.map(lambda _: NamedSharding(mesh, P()), table_np)
    )
    sampler = PerHostSampler(
        make_index_sampler(
            sizes, cfg.n, cfg.k, cfg.q, batch_size=local_b,
            seed=process_seed(0), backend="python",
        ),
        GlobalBatchAssembler(mesh, cfg.batch_size, index_mode=True),
    )

    base = EpisodeSampler(ds, tok, cfg.n, cfg.k, cfg.q, 2, seed=0)
    sup, qry, _ = batch_to_model_inputs(base.sample_batch())
    state = init_state(model, cfg, sup, qry)
    step = make_token_cached_train_step(model, cfg, mesh, state)
    state = shard_state(state, mesh)
    for _ in range(3):
        si, qi, lab = batch_to_model_inputs(sampler.sample_batch())
        state, m = step(state, table, si, qi, lab)

    @jax.jit
    def global_norm(params):
        return sum(
            jnp.sum(jnp.square(x.astype(jnp.float32)))
            for x in jax.tree.leaves(params)
        )

    # float() on fully-replicated multihost outputs is legal; identical
    # values across processes require the collectives to have agreed.
    print(json.dumps({
        "pid": pid,
        "loss": float(m["loss"]),
        "norm": float(global_norm(state.params)),
    }), flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main(int(sys.argv[1]), int(sys.argv[2]))
