"""serving/ tier-1 tests (CPU, synthetic data): registry correctness vs the
direct episodic forward pass, bucket selection/padding, deadline +
backpressure behavior, zero steady-state recompiles, and NOTA verdicts.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.serving.batcher import (
    DeadlineExceeded,
    DynamicBatcher,
    Saturated,
)
from induction_network_on_fewrel_tpu.serving.buckets import (
    QUERY_DTYPES,
    pad_rows,
    select_bucket,
    zero_batch,
)
from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
from induction_network_on_fewrel_tpu.serving.stats import ServingStats

# Tiny flagship-shaped config: cnn encoder (fast CPU compiles), small dims.
CFG = ExperimentConfig(
    model="induction", encoder="cnn", hidden_size=16,
    vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
    induction_dim=8, ntn_slices=4, routing_iters=2,
    n=3, train_n=3, k=2, q=2, device="cpu",
)


@pytest.fixture(scope="module")
def world():
    vocab = make_synthetic_glove(vocab_size=CFG.vocab_size - 2,
                                 word_dim=CFG.word_dim)
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    model = build_model(CFG, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(CFG.max_length, (1, CFG.n, CFG.k)),
        zero_batch(CFG.max_length, (1, 2)),
    )
    ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=8,
        vocab_size=CFG.vocab_size - 2, seed=1,
    )
    return vocab, tok, model, params, ds


def _engine(world, start=False, **kw):
    _, tok, model, params, ds = world
    eng = InferenceEngine(
        model, params, CFG, tok, k=CFG.k,
        buckets=kw.pop("buckets", (1, 2, 4)), start=start, **kw,
    )
    return eng, ds


# --- registry correctness -------------------------------------------------


def test_registry_matches_direct_forward(world):
    """Registry-distilled class vectors + the bucketed query program must
    reproduce the direct episodic forward pass (same params, same math —
    the encoders are row-independent, so split encoding is exact up to
    fusion-order float noise)."""
    _, tok, model, params, ds = world
    eng, _ = _engine(world)
    try:
        names = eng.register_dataset(ds)
        assert names == list(ds.rel_names)

        td = lambda t: (t.word, t.pos1, t.pos2, t.mask)  # noqa: E731
        keys = ("word", "pos1", "pos2", "mask")

        def stack(insts, lead):
            cols = list(zip(*(td(tok(i)) for i in insts)))
            return {
                k: np.stack(cols[j]).astype(QUERY_DTYPES[k])
                .reshape((1,) + lead + (-1,))
                for j, k in enumerate(keys)
            }

        sup = stack(
            [i for r in names for i in ds.instances[r][: CFG.k]],
            (len(names), CFG.k),
        )
        qry = stack([ds.instances[r][-1] for r in names], (len(names),))
        direct = np.asarray(model.apply(params, sup, qry))[0]

        mat = eng.registry.class_matrix()
        assert mat.shape == (len(names), CFG.induction_dim)
        served = eng.programs.run(
            params, mat, {k: qry[k][0] for k in keys}
        )
        assert served.shape == direct.shape
        np.testing.assert_allclose(served, direct, rtol=1e-5, atol=1e-5)
    finally:
        eng.close()


def test_incremental_registration_matches_bulk(world):
    """register() one class at a time == register_dataset's batched distill
    (induction routing is per-class independent)."""
    _, tok, model, params, ds = world
    eng_a, _ = _engine(world)
    eng_b, _ = _engine(world)
    try:
        eng_a.register_dataset(ds)
        for r in ds.rel_names:
            eng_b.register_class(r, ds.instances[r][: CFG.k])
        np.testing.assert_allclose(
            np.asarray(eng_a.registry.class_matrix()),
            np.asarray(eng_b.registry.class_matrix()),
            rtol=1e-5, atol=1e-6,
        )
    finally:
        eng_a.close()
        eng_b.close()


# --- buckets --------------------------------------------------------------


def test_bucket_selection():
    assert select_bucket(1, (1, 2, 4, 8)) == 1
    assert select_bucket(3, (1, 2, 4, 8)) == 4
    assert select_bucket(8, (1, 2, 4, 8)) == 8
    with pytest.raises(ValueError):
        select_bucket(9, (1, 2, 4, 8))
    with pytest.raises(ValueError):
        select_bucket(0, (1, 2, 4))


def test_pad_rows_repeats_first_row():
    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    out = pad_rows(arr, 4)
    assert out.shape == (4, 3)
    np.testing.assert_array_equal(out[:2], arr)
    np.testing.assert_array_equal(out[2], arr[0])
    np.testing.assert_array_equal(out[3], arr[0])
    assert pad_rows(arr, 2) is arr  # no copy when already bucket-sized


def test_zero_recompiles_after_warmup(world):
    """Every bucket compiles exactly once at warmup; steady-state traffic
    of every batch size then reuses those programs (the acceptance gate)."""
    eng, ds = _engine(world, buckets=(1, 2, 4))
    try:
        eng.register_dataset(ds)
        compiled = eng.warmup()
        assert compiled == 3
        assert eng.stats.warmup_compiles == 3
        inst = ds.instances[ds.rel_names[0]][-1]
        for size in (1, 2, 3, 4, 1, 2):
            futs = [eng.submit(inst) for _ in range(size)]
            eng.batcher.drain_once()
            for f in futs:
                assert f.result(timeout=10.0)["label"] in ds.rel_names
        assert eng.stats.steady_compiles == 0
        assert eng.programs.compiles == 3
    finally:
        eng.close()


# --- batcher: deadlines + backpressure ------------------------------------


def test_expired_deadline_fails_fast():
    executed = []
    b = DynamicBatcher(executed.append, buckets=(1, 2), start=False,
                       stats=ServingStats())
    fut = b.submit({"q": 1}, deadline_s=-0.01)  # already expired
    assert b.drain_once() == 0
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=1.0)
    assert executed == []
    assert b._stats.deadline_missed == 1
    b.close()


def test_partial_bucket_flush_under_deadline_pressure():
    """With a huge batch window but a tight oldest-request deadline, the
    collector flushes the partial bucket instead of waiting for more rows."""
    batches = []
    stats = ServingStats()

    def execute(batch):
        batches.append(len(batch))
        for r in batch:
            r.future.set_result("ok")

    b = DynamicBatcher(execute, buckets=(1, 2, 8), batch_window_s=30.0,
                       start=False, stats=stats)
    futs = [b.submit({"q": i}, deadline_s=0.05) for i in range(2)]
    t0 = time.monotonic()
    assert b.drain_once() == 2
    assert time.monotonic() - t0 < 5.0  # nowhere near the 30 s window
    assert batches == [2]               # partial (2 of max 8), one flush
    for f in futs:
        assert f.result(timeout=1.0) == "ok"
    b.close()


def test_backpressure_rejects_with_retry_after():
    stats = ServingStats()
    b = DynamicBatcher(lambda batch: None, buckets=(1, 2),
                       max_queue_depth=2, start=False, stats=stats)
    b.submit({"q": 0}, deadline_s=1.0)
    b.submit({"q": 1}, deadline_s=1.0)
    with pytest.raises(Saturated) as ei:
        b.submit({"q": 2}, deadline_s=1.0)
    assert ei.value.retry_after_s > 0
    assert stats.rejected == 1
    assert b.queue_depth == 2
    b.close()


def test_execute_error_fails_batch_not_worker():
    def boom(batch):
        raise RuntimeError("device fell over")

    b = DynamicBatcher(boom, buckets=(1,), start=False, stats=ServingStats())
    fut = b.submit({"q": 0}, deadline_s=5.0)
    b.drain_once()
    with pytest.raises(RuntimeError, match="fell over"):
        fut.result(timeout=1.0)
    # The batcher survives: the next request still executes.
    fut2 = b.submit({"q": 1}, deadline_s=5.0)
    b.drain_once()
    with pytest.raises(RuntimeError):
        fut2.result(timeout=1.0)
    b.close()


# --- engine end-to-end ----------------------------------------------------


def test_engine_threaded_end_to_end(world):
    """Worker-thread path: concurrent submits resolve to valid verdicts,
    stats populate, and the query path never recompiles after warmup."""
    eng, ds = _engine(world, start=True, batch_window_s=0.005)
    try:
        eng.register_dataset(ds)
        eng.warmup()
        insts = [ds.instances[r][-2] for r in ds.rel_names] * 3
        futs = [eng.submit(i, deadline_s=30.0) for i in insts]
        for f in futs:
            v = f.result(timeout=30.0)
            assert v["label"] in ds.rel_names
            assert not v["nota"]
            assert set(v["logits"]) == set(ds.rel_names)
            assert v["latency_ms"] >= 0
        snap = eng.stats.snapshot(queue_depth=eng.batcher.queue_depth)
        assert snap["served"] == len(futs)
        assert snap["steady_recompiles"] == 0
        assert snap["p50_ms"] > 0 and snap["p99_ms"] >= snap["p50_ms"]
        assert 0 < snap["batch_occupancy"] <= 1.0
    finally:
        eng.close()


def test_nota_verdict(world):
    """A checkpoint trained with na_rate>0 carries the NOTA head; when its
    logit dominates, the engine answers the explicit no_relation verdict."""
    vocab, tok, _, _, ds = world
    cfg = CFG.replace(na_rate=1)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    inner = dict(params["params"])
    inner["nota_logit"] = jnp.full((1,), 50.0)  # force the NOTA verdict
    params = {"params": inner}
    eng = InferenceEngine(model, params, cfg, tok, k=cfg.k,
                          buckets=(1, 2), start=False)
    try:
        eng.register_dataset(ds)
        fut = eng.submit(ds.instances[ds.rel_names[0]][-1], deadline_s=30.0)
        eng.batcher.drain_once()
        v = fut.result(timeout=10.0)
        assert v["nota"] and v["label"] == "no_relation"
        assert v["class_index"] == -1
        assert "no_relation" in v["logits"]
    finally:
        eng.close()


def test_engine_refuses_non_induction(world):
    vocab, tok, _, params, _ = world
    cfg = CFG.replace(model="proto")
    with pytest.raises(ValueError, match="induction"):
        InferenceEngine(build_model(cfg, glove_init=vocab.vectors),
                        params, cfg, tok, start=False)


def test_registry_guards(world):
    eng, ds = _engine(world)
    try:
        with pytest.raises(ValueError, match="no classes registered"):
            eng.submit(ds.instances[ds.rel_names[0]][0])
        with pytest.raises(ValueError, match="at least one instance"):
            eng.registry.register_tokens("empty", [])
    finally:
        eng.close()
