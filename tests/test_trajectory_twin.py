"""Full-model TRAINING-TRAJECTORY golden twin (round-5 VERDICT item 1).

The per-module torch goldens (test_golden_torch.py, test_lstm.py) pin each
forward in isolation; this file pins the COMPOSITION UNDER TRAINING — the
strongest accuracy-parity statement available while the reference mount is
empty (SURVEY.md §4.2, §7: loss choice, optimizer coupling, LR schedule and
init distributions are exactly the levers that move FewRel accuracy by
>=0.3 pt).

A torch-CPU twin of the complete flagship model — embedding (word table ⧺
dual position embeds) -> BiLSTM + structured self-attention -> induction
routing -> NTN relation scorer — is written from the paper equations /
torch conventions (manual LSTM loop, NOT our JAX code transliterated),
loaded with IDENTICAL weights, then driven for 20 steps of
Adam(weight_decay) + global-norm clip + StepLR on IDENTICAL episode
batches. Asserts, per step, that the loss trajectories track, and at the
end that every parameter tensor still matches.

Semantics pinned here (each mirrors a specific config knob):
  * loss: BOTH mse (sigmoid-vs-onehot, paper §3.4) and ce — flag-selected.
  * optimizer: optax chain(clip_by_global_norm, add_decayed_weights, adam)
    == torch clip_grad_norm_ then Adam(weight_decay=...) — COUPLED L2
    (decay enters before moment normalization), torch's convention.
  * schedule: optax exponential_decay(staircase) == torch StepLR stepped
    once per optimizer step; the 20-step run crosses two decay boundaries
    (step_size=7), so an off-by-one in either schedule fails the test.
  * single LSTM bias: our BiLSTM carries ONE bias per direction; the twin's
    manual cell does too (a torch nn.LSTM twin would train bias_ih AND
    bias_hh — that deviation is exactly what a trajectory test must not
    hide, so the twin avoids the module).

Intentional deviations from exactness (documented, not hidden): op
ordering differs between XLA and torch (einsum contraction order, scan vs
python loop), so trajectories diverge at f32 rounding rate. Measured over
20 steps on these shapes: per-step loss drift stays under ~1e-5 relative;
the assertions use 20x headroom (rtol 2e-4 on losses, 1e-3 absolute on
final params whose magnitudes are O(1e-1..1)).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling.episodes import EpisodeSampler
from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

pytestmark = pytest.mark.slow

STEPS = 20


def _cfg(loss: str, embed: str = "shared") -> ExperimentConfig:
    return ExperimentConfig(
        encoder="bilstm", model="induction", loss=loss,
        n=3, k=2, q=2, batch_size=2, max_length=12,
        vocab_size=62, word_dim=16, pos_dim=4,
        lstm_hidden=12, att_dim=8, induction_dim=10, ntn_slices=6,
        routing_iters=3, lstm_backend="scan",
        compute_dtype="float32", head_dtype="float32",
        optimizer="adam", embed_optimizer=embed,
        lr=2e-3, weight_decay=1e-4, grad_clip=1.0,
        lr_step_size=7, lr_gamma=0.5,
    )


def _episode_stream(cfg, n_steps: int):
    vocab = make_synthetic_glove(
        vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
    )
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=cfg.k + cfg.q + 4,
        vocab_size=cfg.vocab_size - 2, sentence_len=(6, cfg.max_length),
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(
        ds, tok, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate, seed=123,
    )
    return [batch_to_model_inputs(sampler.sample_batch()) for _ in range(n_steps)]


def torch_squash(x, eps=1e-12):
    sq = (x**2).sum(-1, keepdim=True)
    return (sq / (1 + sq)) * x / torch.sqrt(sq + eps)


class TorchFlagshipTwin:
    """The complete flagship model + training loop in torch, from equations.

    Parameters are copied from the JAX init (flax Dense kernels are [in,
    out]; torch matmul uses the same layout here, so no transposes — the
    twin multiplies x @ W exactly as the flax modules do).
    """

    def __init__(self, jp, cfg):
        g = lambda *ks: torch.nn.Parameter(
            torch.tensor(np.asarray(_get(jp, ks)), dtype=torch.float32)
        )
        self.word = g("embedding", "word_embedding")
        self.pos1 = g("embedding", "pos1_embedding")
        self.pos2 = g("embedding", "pos2_embedding")
        self.w_ih = g("encoder", "w_ih")        # [2, D, 4u]
        self.w_hh = g("encoder", "w_hh")        # [2, u, 4u]
        self.bias = g("encoder", "bias")        # [2, 4u]  (single bias!)
        self.att_W1 = g("encoder", "att_w1")              # [2u, A]
        self.att_w2 = g("encoder", "att_w2")              # [A, 1]
        self.ind_W = g("induction", "Dense_0", "kernel")  # [2u, C]
        self.ind_b = g("induction", "Dense_0", "bias")
        self.qp_W = g("query_proj", "kernel")             # [2u, C]
        self.qp_b = g("query_proj", "bias")
        self.ntn_M = g("relation", "tensor_slices")       # [H, C, C]
        self.ntn_W = g("relation", "Dense_0", "kernel")   # [H, 1]
        self.ntn_b = g("relation", "Dense_0", "bias")
        self.params = [
            self.word, self.pos1, self.pos2, self.w_ih, self.w_hh,
            self.bias, self.att_W1, self.att_w2, self.ind_W, self.ind_b,
            self.qp_W, self.qp_b, self.ntn_M, self.ntn_W, self.ntn_b,
        ]
        self.cfg = cfg

    # -- model ----------------------------------------------------------
    def _lstm_dir(self, x, d):
        """Manual LSTM over [M, L, D] for direction d (gate order i,f,g,o,
        single bias, zero init state, f32 — torch.nn.LSTM conventions)."""
        M, L, _ = x.shape
        u = self.w_hh.shape[1]
        xs = x if d == 0 else torch.flip(x, dims=(1,))
        h = torch.zeros(M, u)
        c = torch.zeros(M, u)
        hs = []
        for t in range(L):
            a = xs[:, t] @ self.w_ih[d] + h @ self.w_hh[d] + self.bias[d]
            i = torch.sigmoid(a[:, :u])
            f = torch.sigmoid(a[:, u : 2 * u])
            gg = torch.tanh(a[:, 2 * u : 3 * u])
            o = torch.sigmoid(a[:, 3 * u :])
            c = f * c + i * gg
            h = o * torch.tanh(c)
            hs.append(h)
        H = torch.stack(hs, dim=1)              # [M, L, u]
        return H if d == 0 else torch.flip(H, dims=(1,))

    def encode(self, dct):
        word = torch.tensor(np.asarray(dct["word"], np.int64))
        p1 = torch.tensor(np.asarray(dct["pos1"], np.int64))
        p2 = torch.tensor(np.asarray(dct["pos2"], np.int64))
        mask = torch.tensor(np.asarray(dct["mask"], np.float32))
        lead = word.shape[:-1]
        L = word.shape[-1]
        word, p1, p2, mask = (
            t.reshape(-1, L) for t in (word, p1, p2, mask)
        )
        emb = torch.cat(
            [self.word[word], self.pos1[p1], self.pos2[p2]], dim=-1
        )                                         # [M, L, D]
        H = torch.cat(
            [self._lstm_dir(emb, 0), self._lstm_dir(emb, 1)], dim=-1
        )                                         # [M, L, 2u]
        scores = (torch.tanh(H @ self.att_W1) @ self.att_w2)[..., 0]
        # exact masked-softmax twin of ops.core.masked_softmax
        s = torch.where(mask > 0, scores, torch.tensor(-1e30))
        s = s - s.max(dim=-1, keepdim=True).values
        e = torch.exp(s) * (mask > 0)
        att = e / (e.sum(dim=-1, keepdim=True) + 1e-13)
        out = torch.einsum("ml,mlh->mh", att, H)
        return out.reshape(*lead, -1)

    def forward(self, support, query):
        sup = self.encode(support)                # [B, N, K, 2u]
        qry = self.encode(query)                  # [B, TQ, 2u]
        e_hat = torch_squash(sup @ self.ind_W + self.ind_b)
        B, N, K, _ = e_hat.shape
        b = torch.zeros(B, N, K)
        for _ in range(self.cfg.routing_iters):
            d = torch.softmax(b, dim=-1)
            c = torch_squash(torch.einsum("bnk,bnkc->bnc", d, e_hat))
            b = b + torch.einsum("bnkc,bnc->bnk", e_hat, c)
        d = torch.softmax(b, dim=-1)
        c = torch_squash(torch.einsum("bnk,bnkc->bnc", d, e_hat))
        qc = qry @ self.qp_W + self.qp_b
        cM = torch.einsum("bnc,hcd->bnhd", c, self.ntn_M)
        v = torch.relu(torch.einsum("bnhd,bqd->bqnh", cM, qc))
        return (v @ self.ntn_W + self.ntn_b)[..., 0]   # [B, TQ, N]

    def loss(self, logits, label):
        label = torch.tensor(np.asarray(label, np.int64))
        if self.cfg.loss == "mse":
            onehot = torch.nn.functional.one_hot(
                label, logits.shape[-1]
            ).float()
            return ((torch.sigmoid(logits) - onehot) ** 2).mean()
        return torch.nn.functional.cross_entropy(
            logits.reshape(-1, logits.shape[-1]), label.reshape(-1)
        )

    # -- training loop --------------------------------------------------
    def train(self, batches):
        cfg = self.cfg
        if cfg.embed_optimizer == "lazy":
            # The ONE documented lazy-vs-dense delta (train/lazy_embed.py,
            # BASELINE.md round-3): weight decay is EXCLUDED on the word
            # table — torch expresses it as a wd=0 param group. Everything
            # else (Adam math, clip over ALL grads, schedule) is shared.
            groups = [
                {"params": [self.word], "weight_decay": 0.0},
                {"params": [p for p in self.params if p is not self.word],
                 "weight_decay": cfg.weight_decay},
            ]
        else:
            groups = [
                {"params": self.params, "weight_decay": cfg.weight_decay}
            ]
        opt = torch.optim.Adam(groups, lr=cfg.lr, betas=(0.9, 0.999), eps=1e-8)
        sched = torch.optim.lr_scheduler.StepLR(
            opt, step_size=cfg.lr_step_size, gamma=cfg.lr_gamma
        )
        losses = []
        for support, query, label in batches:
            opt.zero_grad()
            out = self.loss(self.forward(support, query), label)
            out.backward()
            torch.nn.utils.clip_grad_norm_(self.params, cfg.grad_clip)
            opt.step()
            sched.step()
            losses.append(float(out.detach()))
        return losses


def _get(tree, keys):
    for k in keys:
        tree = tree[k]
    return tree


@pytest.mark.parametrize("loss", ["mse", "ce"])
def test_training_trajectory_matches_torch(loss):
    cfg = _cfg(loss)
    batches = _episode_stream(cfg, STEPS)
    model = build_model(cfg)

    sup0, qry0, _ = batches[0]
    state = init_state(model, cfg, sup0, qry0)
    p_init = jax.tree.map(np.asarray, state.params["params"])
    twin = TorchFlagshipTwin(p_init, cfg)

    step = make_train_step(model, cfg)
    jax_losses = []
    for support, query, label in batches:
        state, metrics = step(state, support, query, jnp.asarray(label))
        jax_losses.append(float(metrics["loss"]))

    torch_losses = twin.train(batches)

    # Per-step losses: the trajectory must TRACK, not just end close —
    # optimizer coupling / schedule / clip bugs show up mid-trajectory.
    np.testing.assert_allclose(
        jax_losses, torch_losses, rtol=2e-4, atol=1e-6,
        err_msg=f"loss trajectory diverged ({loss})",
    )
    # Anti-triviality: a frozen model would "match" trivially. MSE has
    # strong gradient at the near-zero-logit init (sigmoid(0)=0.5 vs
    # one-hot) so its loss visibly falls; CE at near-uniform logits is
    # QUADRATICALLY insensitive (measured flat to ~1e-6 over 20 steps on
    # these shapes) — there the meaningful movement is in the parameters,
    # which Adam advances at ~lr per step regardless of gradient scale
    # (measured max |Δparam| ≈ 2.4e-2). Both regimes assert the model
    # actually trained before comparing final params.
    if loss == "mse":
        assert jax_losses[-1] < jax_losses[0]
    jp_now = jax.tree.map(np.asarray, state.params["params"])
    moved = max(
        float(np.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(p_init), jax.tree.leaves(jp_now))
    )
    assert moved > 1e-3, f"params barely moved ({moved:.2e}) — dead model?"

    # Final parameters: every tensor, after 20 coupled Adam+StepLR updates.
    jp = jp_now
    pairs = {
        "word_embedding": (("embedding", "word_embedding"), twin.word),
        "pos1_embedding": (("embedding", "pos1_embedding"), twin.pos1),
        "pos2_embedding": (("embedding", "pos2_embedding"), twin.pos2),
        "w_ih": (("encoder", "w_ih"), twin.w_ih),
        "w_hh": (("encoder", "w_hh"), twin.w_hh),
        "bias": (("encoder", "bias"), twin.bias),
        "att_W1": (("encoder", "att_w1"), twin.att_W1),
        "att_w2": (("encoder", "att_w2"), twin.att_w2),
        "ind_W": (("induction", "Dense_0", "kernel"), twin.ind_W),
        "ind_b": (("induction", "Dense_0", "bias"), twin.ind_b),
        "qp_W": (("query_proj", "kernel"), twin.qp_W),
        "qp_b": (("query_proj", "bias"), twin.qp_b),
        "ntn_M": (("relation", "tensor_slices"), twin.ntn_M),
        "ntn_W": (("relation", "Dense_0", "kernel"), twin.ntn_W),
        "ntn_b": (("relation", "Dense_0", "bias"), twin.ntn_b),
    }
    for name, (keys, t) in pairs.items():
        np.testing.assert_allclose(
            _get(jp, keys), t.detach().numpy(), rtol=1e-3, atol=1e-3,
            err_msg=f"param {name} diverged after {STEPS} steps ({loss})",
        )


def test_lazy_training_trajectory_matches_torch():
    """The LAZY embedding path against an independent torch twin: same
    trajectory as dense Adam with the table's weight decay OFF (the one
    documented config delta — asserted here end-to-end, not just in
    prose). test_lazy_embed.py pins lazy == wd-free-dense at 1e-6 within
    JAX; this closes the triangle to torch."""
    cfg = _cfg("mse", embed="lazy")
    batches = _episode_stream(cfg, STEPS)
    model = build_model(cfg)

    sup0, qry0, _ = batches[0]
    state = init_state(model, cfg, sup0, qry0)
    p_init = jax.tree.map(np.asarray, state.params["params"])
    twin = TorchFlagshipTwin(p_init, cfg)

    step = make_train_step(model, cfg)
    jax_losses = []
    for support, query, label in batches:
        state, metrics = step(state, support, query, jnp.asarray(label))
        jax_losses.append(float(metrics["loss"]))
    # Catch the lazily-deferred rows up to state.step — the exact
    # dense-equivalent table (what checkpoints/eval see at boundaries).
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        make_materialize,
    )

    state = make_materialize(cfg)(state)

    torch_losses = twin.train(batches)
    np.testing.assert_allclose(
        jax_losses, torch_losses, rtol=2e-4, atol=1e-6,
        err_msg="lazy loss trajectory diverged",
    )
    assert jax_losses[-1] < jax_losses[0]
    jp = jax.tree.map(np.asarray, state.params["params"])
    np.testing.assert_allclose(
        _get(jp, ("embedding", "word_embedding")),
        twin.word.detach().numpy(), rtol=1e-3, atol=1e-3,
        err_msg="lazy word table diverged from torch wd-free twin",
    )
    np.testing.assert_allclose(
        _get(jp, ("relation", "tensor_slices")),
        twin.ntn_M.detach().numpy(), rtol=1e-3, atol=1e-3,
    )


def test_schedule_decay_boundaries_crossed():
    """Self-check on the test's own regime: with step_size=7 over 20 steps
    the staircase must decay twice — guards against a future config edit
    silently removing the schedule from what the twin pins."""
    cfg = _cfg("mse")
    import optax

    sched = optax.exponential_decay(
        cfg.lr, cfg.lr_step_size, cfg.lr_gamma, staircase=True
    )
    lrs = {float(sched(i)) for i in range(STEPS)}
    assert len(lrs) == 3  # init, /2, /4
