"""Exact-parity proof for the lazy word-table Adam (train/lazy_embed.py).

VERDICT round-2 item 3: the lazy scheme must be mathematically equivalent
to dense Adam on the table — verified here at 1e-6 over >=12 steps against
the dense optimizer, INCLUDING untouched rows and rows with momentum tails
(touched early, then skipped for many steps). The staircase LR schedule is
set to cross boundaries inside catch-up windows so the schedule replication
is exercised, not just constant-lr decay.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
from induction_network_on_fewrel_tpu.train.lazy_embed import (
    find_emb_path,
    make_materialize,
    tree_get,
)
from induction_network_on_fewrel_tpu.train.steps import (
    init_state,
    make_multi_train_step,
    make_train_step,
)

VOCAB = 52  # 50 GloVe words + UNK/BLANK; the synthetic corpus uses only 20
CFG = ExperimentConfig(
    encoder="cnn", n=3, k=2, q=2, batch_size=2, max_length=12,
    vocab_size=VOCAB, hidden_size=16, lr=3e-3, lr_step_size=3,  # staircase
    weight_decay=0.0, grad_clip=10.0,                            # inside run
)
STEPS = 12


@pytest.fixture(scope="module")
def fixture():
    vocab = make_synthetic_glove(vocab_size=VOCAB - 2)
    # Small per-relation pools + tiny episodes => each batch touches only a
    # slice of the 20 active words: real gaps form, and rows 22..51 are
    # never touched at all.
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=6, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    sampler = EpisodeSampler(ds, tok, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=3)
    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(STEPS)]
    model = build_model(CFG, glove_init=vocab.vectors)
    return model, vocab, batches


def _run(model, cfg, batches, state=None):
    step = make_train_step(model, cfg)
    state = state if state is not None else init_state(
        model, cfg, batches[0][0], batches[0][1]
    )
    for sup, qry, lab in batches:
        state, _ = step(state, sup, qry, lab)
    return state


def _assert_trees_close(a, b, atol):
    flat_a = jax.tree_util.tree_flatten_with_path(a)[0]
    flat_b = dict(
        (jax.tree_util.keystr(p), v)
        for p, v in jax.tree_util.tree_flatten_with_path(b)[0]
    )
    for path, va in flat_a:
        vb = flat_b[jax.tree_util.keystr(path)]
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), atol=atol, rtol=0,
            err_msg=f"param {jax.tree_util.keystr(path)} diverged",
        )


@pytest.mark.slow
def test_lazy_equals_dense_adam(fixture):
    """Lazy trajectory == dense shared-Adam trajectory at 1e-6 (wd=0, so
    the two configs define the SAME optimizer), every param including the
    full table: touched rows, momentum-tail rows, and never-touched rows."""
    model, vocab, batches = fixture
    dense = _run(model, CFG.replace(embed_optimizer="shared"), batches)
    lazy_cfg = CFG.replace(embed_optimizer="lazy")
    raw = _run(model, lazy_cfg, batches)
    # Gap evidence BEFORE materialize (which catches every row up): some
    # row was touched at an earlier step but not the last one — its
    # catch-up loop ran with gap > 0 during training.
    last = np.asarray(raw.emb_last)
    assert ((last > 0) & (last < STEPS)).any(), "no gapped rows exercised"
    lazy = make_materialize(lazy_cfg)(raw)

    path = find_emb_path(dense.params)
    table_d = np.asarray(tree_get(dense.params, path))
    table_l = np.asarray(tree_get(lazy.params, path))
    np.testing.assert_allclose(table_l, table_d, atol=1e-6, rtol=0)
    # Never-touched rows stayed EXACTLY at init in both modes (m=v=0 =>
    # zero Adam update) — the structural fact laziness exploits.
    touched = np.zeros(VOCAB, bool)
    for sup, qry, _ in batches:
        touched[np.asarray(sup["word"]).ravel()] = True
        touched[np.asarray(qry["word"]).ravel()] = True
    assert (~touched).sum() >= 10, "fixture lost its untouched rows"
    np.testing.assert_array_equal(
        table_l[~touched], np.asarray(vocab.vectors)[~touched]
    )
    # The non-embedding params went through the identical optax path.
    _assert_trees_close(lazy.params, dense.params, atol=1e-6)


@pytest.mark.slow
def test_lazy_with_weight_decay_matches_nowd_table_twin(fixture):
    """With wd>0, lazy == the dense twin that applies wd everywhere EXCEPT
    the table (the documented lazy semantics): coupled-L2 Adam on the main
    partition, plain Adam on the table."""
    model, _, batches = fixture
    wd = 1e-2  # large enough that a wd mismatch would exceed 1e-6 in 1 step
    lazy_cfg = CFG.replace(embed_optimizer="lazy", weight_decay=wd)
    lazy = _run(model, lazy_cfg, batches)
    lazy = make_materialize(lazy_cfg)(lazy)

    schedule = optax.exponential_decay(
        init_value=CFG.lr, transition_steps=CFG.lr_step_size,
        decay_rate=CFG.lr_gamma, staircase=True,
    )

    def label_fn(params):
        return jax.tree_util.tree_map_with_path(
            lambda p, _: "emb" if any(
                getattr(k, "key", None) == "word_embedding" for k in p
            ) else "main",
            params,
        )

    twin_tx = optax.chain(
        optax.clip_by_global_norm(CFG.grad_clip),
        optax.multi_transform(
            {
                "main": optax.chain(
                    optax.add_decayed_weights(wd), optax.adam(schedule)
                ),
                "emb": optax.adam(schedule),
            },
            label_fn,
        ),
    )
    from induction_network_on_fewrel_tpu.train.steps import TrainState

    params = model.init(jax.random.key(CFG.seed), batches[0][0], batches[0][1])
    twin_state = TrainState.create(
        apply_fn=model.apply, params=params, tx=twin_tx
    )
    twin = _run(
        model, CFG.replace(embed_optimizer="shared", weight_decay=wd),
        batches, state=twin_state,
    )
    _assert_trees_close(lazy.params, twin.params, atol=1e-6)


@pytest.mark.slow
def test_lazy_fused_scan_matches_per_step(fixture):
    """The steps_per_call scan threads the lazy state through its carry:
    4 fused calls of 3 steps == 12 per-step calls, bitwise-close."""
    model, _, batches = fixture
    lazy_cfg = CFG.replace(embed_optimizer="lazy", steps_per_call=3)
    per_step = _run(model, lazy_cfg, batches)

    multi = make_multi_train_step(model, lazy_cfg)
    state = init_state(model, lazy_cfg, batches[0][0], batches[0][1])
    for i in range(0, STEPS, 3):
        sup_s, qry_s, lab_s = jax.tree.map(
            lambda *xs: np.stack(xs), *batches[i : i + 3]
        )
        state, _ = multi(state, sup_s, qry_s, lab_s)

    mat = make_materialize(lazy_cfg)
    _assert_trees_close(
        mat(state).params, mat(per_step).params, atol=1e-6
    )


@pytest.mark.slow
def test_lazy_token_cache_matches_dense(fixture):
    """The token-cache lazy body (static corpus remap, no per-step dedup)
    computes the identical trajectory as the dense cached step — same
    index stream, params equal at 1e-6 after 10 steps."""
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        augment_token_table,
    )
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_train_step,
        tokenize_dataset,
    )

    model, vocab, batches = fixture
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=6, vocab_size=35, seed=9
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    aug, uids = augment_token_table(table_np)
    lazy_table = {**aug, "uids": uids}
    sampler = make_index_sampler(
        sizes, CFG.n, CFG.k, CFG.q, batch_size=CFG.batch_size, seed=4,
        backend="python",
    )
    idx_batches = [sampler.sample_batch() for _ in range(10)]

    def run(cfg, table):
        step = make_token_cached_train_step(model, cfg)
        state = init_state(model, cfg, batches[0][0], batches[0][1])
        for b in idx_batches:
            state, _ = step(state, table, b.support_idx, b.query_idx, b.label)
        return state

    dense = run(CFG.replace(embed_optimizer="shared"), table_np)
    lazy_cfg = CFG.replace(embed_optimizer="lazy")
    lazy = make_materialize(lazy_cfg)(run(lazy_cfg, lazy_table))
    _assert_trees_close(lazy.params, dense.params, atol=1e-6)


@pytest.mark.slow
def test_lazy_checkpoint_resume_trajectory(fixture, tmp_path):
    """Save-at-boundary + restore + continue == uninterrupted run: the
    checkpoint stores the MATERIALIZED table plus the lazy Adam state, so
    the resumed catch-up math continues exactly."""
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )

    model, _, batches = fixture
    lazy_cfg = CFG.replace(embed_optimizer="lazy")
    mat = make_materialize(lazy_cfg)
    step = make_train_step(model, lazy_cfg)

    # Uninterrupted: 12 steps.
    full = init_state(model, lazy_cfg, batches[0][0], batches[0][1])
    for sup, qry, lab in batches:
        full, _ = step(full, sup, qry, lab)

    # Interrupted at 6: materialize (as the trainer does at boundaries),
    # save, restore into a fresh state, continue 6 more.
    half = init_state(model, lazy_cfg, batches[0][0], batches[0][1])
    for sup, qry, lab in batches[:6]:
        half, _ = step(half, sup, qry, lab)
    half = mat(half)
    mgr = CheckpointManager(tmp_path, lazy_cfg)
    mgr.save(6, half, val_accuracy=0.5)
    target = jax.device_get(
        init_state(model, lazy_cfg, batches[0][0], batches[0][1])
    )
    restored, step_no = mgr.restore_best(target)
    mgr.close()
    assert step_no == 6
    for sup, qry, lab in batches[6:]:
        restored, _ = step(restored, sup, qry, lab)

    _assert_trees_close(mat(restored).params, mat(full).params, atol=1e-6)


@pytest.mark.slow
def test_lazy_token_cache_on_mesh_matches_dense_on_mesh(fixture):
    """The cached lazy body under GSPMD (dp=8 mesh) == the DENSE cached
    step on the same mesh at 1e-6 — the apples-to-apples equivalence
    (mesh-vs-single carries ~1e-4 of psum reduction-order drift for dense
    and lazy alike, measured identical for both)."""
    import jax.numpy as jnp

    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import shard_state
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        augment_token_table,
    )
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_train_step,
        tokenize_dataset,
    )

    model, vocab, batches = fixture
    lazy_cfg = CFG.replace(embed_optimizer="lazy", batch_size=8)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=8, vocab_size=35, seed=11
    )
    tok = GloveTokenizer(vocab, max_length=CFG.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    aug, uids = augment_token_table(table_np)
    lazy_table = {**aug, "uids": uids}
    sampler = make_index_sampler(
        sizes, lazy_cfg.n, lazy_cfg.k, lazy_cfg.q,
        batch_size=lazy_cfg.batch_size, seed=5, backend="python",
    )
    idx_batches = [sampler.sample_batch() for _ in range(6)]
    mesh = make_mesh(dp=8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    def run_meshed(cfg, table):
        state = init_state(model, cfg, batches[0][0], batches[0][1])
        step = make_token_cached_train_step(model, cfg, mesh, state)
        state = shard_state(state, mesh)
        table = jax.device_put(
            table,
            jax.tree.map(lambda _: NamedSharding(mesh, P()), table),
        )
        for b in idx_batches:
            state, _ = step(
                state, table, b.support_idx, b.query_idx, b.label
            )
        return jax.device_get(state)

    dense = run_meshed(CFG.replace(embed_optimizer="shared", batch_size=8),
                       table_np)
    lazy = run_meshed(lazy_cfg, lazy_table)
    lazy = make_materialize(lazy_cfg)(lazy)
    _assert_trees_close(lazy.params, dense.params, atol=1e-6)


@pytest.mark.slow
def test_convert_lazy_to_dense_continues_exactly(fixture):
    """tools/convert_lazy_ckpt.convert_state: a lazy run converted to a
    dense TrainState mid-stream and continued in SHARED mode reproduces
    the uninterrupted dense trajectory at 1e-6 — moments, bias-correction
    counters, and schedule counters all carried faithfully."""
    import os
    import sys

    tools_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    from convert_lazy_ckpt import convert_state

    model, _, batches = fixture
    lazy_cfg = CFG.replace(embed_optimizer="lazy")
    dense_cfg = CFG.replace(embed_optimizer="shared")

    # Uninterrupted dense reference: 12 steps.
    dense_ref = _run(model, dense_cfg, batches)

    # Lazy for 6 steps -> materialize -> convert -> dense for 6 more.
    step = make_train_step(model, lazy_cfg)
    state = init_state(model, lazy_cfg, batches[0][0], batches[0][1])
    for sup, qry, lab in batches[:6]:
        state, _ = step(state, sup, qry, lab)
    state = make_materialize(lazy_cfg)(state)
    dense = convert_state(
        state, model, dense_cfg, find_emb_path(state.params)
    )
    assert int(dense.step) == 6
    dense_step = make_train_step(model, dense_cfg)
    for sup, qry, lab in batches[6:]:
        dense, _ = dense_step(dense, sup, qry, lab)

    _assert_trees_close(dense.params, dense_ref.params, atol=1e-6)


def test_materialize_is_idempotent(fixture):
    model, _, batches = fixture
    lazy_cfg = CFG.replace(embed_optimizer="lazy")
    state = _run(model, lazy_cfg, batches)
    mat = make_materialize(lazy_cfg)
    once = mat(state)
    twice = mat(jax.tree.map(jnp.copy, once))
    _assert_trees_close(twice.params, once.params, atol=0)
    np.testing.assert_array_equal(
        np.asarray(twice.emb_last), np.asarray(once.emb_last)
    )
