"""Device-resident token cache (train/token_cache.py): the index path must
be a pure transport change — same episodes produce bitwise-identical
training to the live token path."""

import jax
import numpy as np

from induction_network_on_fewrel_tpu.config import ExperimentConfig
from induction_network_on_fewrel_tpu.data import (
    GloveTokenizer,
    make_synthetic_fewrel,
    make_synthetic_glove,
)
from induction_network_on_fewrel_tpu.models import build_model
from induction_network_on_fewrel_tpu.train.feature_cache import (
    FeatureEpisodeSampler,
)
from induction_network_on_fewrel_tpu.train.steps import (
    init_state,
    make_train_step,
)
from induction_network_on_fewrel_tpu.train.token_cache import (
    make_token_cached_eval_step,
    make_token_cached_multi_train_step,
    make_token_cached_train_step,
    tokenize_dataset,
)

L = 16
CFG = ExperimentConfig(
    encoder="cnn", n=3, k=2, q=2, batch_size=4, max_length=L, vocab_size=302,
    compute_dtype="float32", lr=1e-3, weight_decay=0.0,
)


def _setup():
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=10, vocab_size=300
    )
    tok = GloveTokenizer(vocab, max_length=L)
    model = build_model(CFG, glove_init=vocab.vectors)
    table, sizes = tokenize_dataset(ds, tok)
    return model, table, sizes


def test_tokenize_dataset_shapes_and_dtypes():
    _, table, sizes = _setup()
    M = sum(sizes)
    assert table["word"].shape == (M, L) and table["word"].dtype == np.int32
    assert table["pos1"].dtype == np.int16 and table["pos2"].dtype == np.int16
    assert table["mask"].dtype == np.int8
    assert len(sizes) == 6 and all(s == 10 for s in sizes)


def test_size_only_sampler_matches_array_sampler_indices():
    """FeatureEpisodeSampler(sizes) draws the same index stream as
    FeatureEpisodeSampler(arrays, return_indices=True) for the same seed."""
    _, table, sizes = _setup()
    blocks = [np.zeros((m, 4), np.float32) for m in sizes]
    a = FeatureEpisodeSampler(sizes, 3, 2, 2, 4, na_rate=1, seed=5)
    b = FeatureEpisodeSampler(blocks, 3, 2, 2, 4, na_rate=1, seed=5,
                              return_indices=True)
    ba, bb = a.sample_batch(), b.sample_batch()
    np.testing.assert_array_equal(ba.support_idx, bb.support_idx)
    np.testing.assert_array_equal(ba.query_idx, bb.query_idx)
    np.testing.assert_array_equal(ba.label, bb.label)


def test_token_cached_step_equals_live_step_on_same_episode():
    """Gathering tokens on device from indices == feeding the same tokens
    directly: identical loss and identical updated params."""
    model, table, sizes = _setup()
    sampler = FeatureEpisodeSampler(
        sizes, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=2
    )
    batch = sampler.sample_batch()
    # Host-side gather reproduces exactly what the live path would feed
    # (including models/build.py's wire dtypes, which tokenize_dataset
    # already applied).
    sup = {k: v[batch.support_idx] for k, v in table.items()}
    qry = {k: v[batch.query_idx] for k, v in table.items()}

    state_a = init_state(model, CFG, sup, qry)
    state_b = jax.tree.map(
        lambda x: x.copy() if hasattr(x, "copy") else x, state_a
    )
    live = make_train_step(model, CFG)
    cached = make_token_cached_train_step(model, CFG)
    dev_table = jax.device_put(table)

    state_a, m_a = live(state_a, sup, qry, batch.label)
    state_b, m_b = cached(
        state_b, dev_table, batch.support_idx, batch.query_idx, batch.label
    )
    np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]),
                               rtol=1e-6, atol=1e-7)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state_a.params)),
        jax.tree.leaves(jax.device_get(state_b.params)),
    ):
        np.testing.assert_array_equal(a, b)


def test_token_cached_multi_step_and_eval():
    """Fused S-step scan over stacked index batches trains (finite metrics,
    params move); the eval step scores against the same table."""
    model, table, sizes = _setup()
    sampler = FeatureEpisodeSampler(
        sizes, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=3
    )
    dev_table = jax.device_put(table)
    b0 = sampler.sample_batch()
    sup = {k: v[b0.support_idx] for k, v in table.items()}
    qry = {k: v[b0.query_idx] for k, v in table.items()}
    state = init_state(model, CFG, sup, qry)

    S = 3
    batches = [sampler.sample_batch() for _ in range(S)]
    si = np.stack([b.support_idx for b in batches])
    qi = np.stack([b.query_idx for b in batches])
    lab = np.stack([b.label for b in batches])
    multi = make_token_cached_multi_train_step(model, CFG)
    state, metrics = multi(state, dev_table, si, qi, lab)
    assert metrics["loss"].shape == (S,)
    assert np.isfinite(np.asarray(metrics["loss"])).all()

    ev = make_token_cached_eval_step(model, CFG)
    out = ev(state.params, dev_table, b0.support_idx, b0.query_idx, b0.label)
    assert np.isfinite(float(out["loss"]))


def test_token_cached_mesh_step_matches_single_device():
    """(dp=2) GSPMD token-cached step == single-device token-cached step."""
    from induction_network_on_fewrel_tpu.parallel import make_mesh

    model, table, sizes = _setup()
    sampler = FeatureEpisodeSampler(
        sizes, CFG.n, CFG.k, CFG.q, CFG.batch_size, seed=4
    )
    b0 = sampler.sample_batch()
    sup = {k: v[b0.support_idx] for k, v in table.items()}
    qry = {k: v[b0.query_idx] for k, v in table.items()}
    state_a = init_state(model, CFG, sup, qry)
    state_b = jax.tree.map(
        lambda x: x.copy() if hasattr(x, "copy") else x, state_a
    )

    single = make_token_cached_train_step(model, CFG)
    mesh = make_mesh(dp=2, devices=jax.devices()[:2])
    sharded = make_token_cached_train_step(model, CFG, mesh, state_a)
    from jax.sharding import NamedSharding, PartitionSpec

    tab_repl = {
        k: jax.device_put(v, NamedSharding(mesh, PartitionSpec()))
        for k, v in table.items()
    }
    dev_table = jax.device_put(table)

    for _ in range(2):
        b = sampler.sample_batch()
        state_a, m_a = single(
            state_a, dev_table, b.support_idx, b.query_idx, b.label
        )
        state_b, m_b = sharded(
            state_b, tab_repl, b.support_idx, b.query_idx, b.label
        )
        np.testing.assert_allclose(
            float(m_a["loss"]), float(m_b["loss"]), rtol=1e-5, atol=1e-6
        )
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state_a.params)),
        jax.tree.leaves(jax.device_get(state_b.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


def test_fused_cached_eval_matches_per_batch():
    """make_token_cached_multi_eval_step == S per-batch cached evals."""
    import jax
    import numpy as np

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.train.feature_cache import (
        FeatureEpisodeSampler,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_eval_step,
        make_token_cached_multi_eval_step,
        tokenize_dataset,
    )

    cfg = ExperimentConfig(
        encoder="cnn", n=3, k=2, q=2, batch_size=2, max_length=16,
        vocab_size=302, compute_dtype="float32", hidden_size=32,
        induction_dim=16, ntn_slices=8, na_rate=1, steps_per_call=3,
    )
    vocab = make_synthetic_glove(vocab_size=300)
    ds = make_synthetic_fewrel(num_relations=6, instances_per_relation=10,
                               vocab_size=300)
    tok = GloveTokenizer(vocab, max_length=16)
    table_np, sizes = tokenize_dataset(ds, tok)
    table = jax.device_put(table_np)
    sampler = FeatureEpisodeSampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate, seed=0,
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    b0 = sampler.sample_batch()
    sup = {k: v[b0.support_idx] for k, v in table_np.items()}
    qry = {k: v[b0.query_idx] for k, v in table_np.items()}
    params = init_state(model, cfg, sup, qry).params

    si, qi, lab = sampler.sample_fused(3)
    single = make_token_cached_eval_step(model, cfg)
    multi = make_token_cached_multi_eval_step(model, cfg)
    fused = jax.device_get(multi(params, table, si, qi, lab))
    for s in range(3):
        one = jax.device_get(single(params, table, si[s], qi[s], lab[s]))
        for k in one:
            np.testing.assert_allclose(
                np.asarray(fused[k][s]), np.asarray(one[k]),
                rtol=1e-6, atol=1e-6,
            )
    assert "nota_tp" in fused  # NOTA metrics ride the fused path too


def test_pos_offsets_bitwise_equal_full_ids():
    """_compact_pos_offsets + the Embedding's windowed-matmul
    reconstruction produce BITWISE-identical embeddings to the full-id
    gather form (the one-hot row selection is exact in f32), for both the
    time-major (bilstm) and batch-major (cnn) entries; and the compaction
    refuses non-linear position ids."""
    from induction_network_on_fewrel_tpu.models.base import FewShotModel
    from induction_network_on_fewrel_tpu.train.token_cache import (
        _compact_pos_offsets,
    )

    vocab = make_synthetic_glove(vocab_size=80)
    ds = make_synthetic_fewrel(
        num_relations=4, instances_per_relation=6, vocab_size=60
    )
    tok = GloveTokenizer(vocab, max_length=10)
    table, _ = tokenize_dataset(ds, tok)
    assert table["pos1"].ndim == 1, "tokenizer ids are linear -> compacted"
    # Reconstruct the full ids the compaction removed.
    full1 = table["pos1"].astype(np.int32)[:, None] + np.arange(10)
    full2 = table["pos2"].astype(np.int32)[:, None] + np.arange(10)

    for enc in ("bilstm", "cnn"):
        cfg = ExperimentConfig(
            encoder=enc, n=2, k=2, q=1, batch_size=1, max_length=10,
            vocab_size=82, compute_dtype="float32", lstm_hidden=8,
            att_dim=4, hidden_size=8, induction_dim=4, ntn_slices=2,
        )
        model = build_model(cfg, glove_init=vocab.vectors)
        idx = np.arange(4)
        kw = dict(method=FewShotModel.encode)
        args_full = (
            table["word"][idx], full1[idx], full2[idx], table["mask"][idx]
        )
        args_off = (
            table["word"][idx], table["pos1"][idx], table["pos2"][idx],
            table["mask"][idx],
        )
        params = model.init(jax.random.key(0), *args_off, **kw)
        out_off = model.apply(params, *args_off, **kw)
        out_full = model.apply(params, *args_full, **kw)
        np.testing.assert_array_equal(
            np.asarray(out_off), np.asarray(out_full), err_msg=enc
        )

    # Non-linear ids (a BERT-marker-style jump) must NOT compact.
    broken = dict(table)
    broken["pos1"] = full1.astype(np.int16)
    broken["pos1"][0, 5] += 3
    out = _compact_pos_offsets(
        {**broken, "pos2": full2.astype(np.int16)}
    )
    assert out["pos1"].ndim == 2  # left as full ids
    assert out["pos2"].ndim == 1  # still-linear sibling compacts
