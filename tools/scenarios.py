#!/usr/bin/env python3
"""Model-quality scenario harness (ISSUE 10): the regression-gated eval
layer for FewRel 2.0 domain adaptation, open-world NOTA, and noisy /
adversarial episodes — ROADMAP item 3 with the same artifact discipline
as perf (ROOFLINE), comms (COMMS), and latency (SERVE).

Three scenario families, all CPU-honest on the synthetic corpus (the
sandbox has no FewRel files; the synthetic generator plants a learnable
per-relation trigger signal, and ``make_domain_shifted_fewrel`` moves
that signal to a disjoint vocabulary block — the wiki -> pubmed transfer
in miniature):

* **Cross-domain (DA)** — train on the source domain, evaluate on the
  source (in-domain) and on shifted twins at each ``--shift`` (accuracy
  with the existing ``acc_ci95``). A second arm trains through the
  datapipe mixture machinery (``datapipe/mixture.MixtureSchedule`` —
  the FewRel 2.0 wiki+pubmed curriculum spelling) and shows how much of
  the cross-domain cliff a mixture ramp recovers.
* **NOTA calibration** — sweep the none-of-the-above decision threshold
  over a quantile grid of operating points (precision/recall/F1 per
  tau, per ``na_rate``), pick the best-F1 point, and record the quality
  BASELINE at that point (nota_rate / margin / entropy mean+std via the
  shared ``obs/drift.quality_features``) — exactly the calibration
  baseline ``obs/drift.DriftDetector.set_baseline`` consumes at publish
  time.
* **Adversarial** — re-evaluate the trained model on episodes whose
  QUERIES pass through ``datapipe/faults``-style perturbations
  (token noise, truncation, constant-garbage rows; supports stay clean,
  matching the serving split where class vectors distill once).

Artifact: ``SCENARIOS_r*.json`` — full-mode results plus a ``tier1``
section (the miniature run + regression band) that
``tests/test_scenarios.py`` replays IN-PROCESS against the committed
artifact, the same pattern as tests/test_roofline.py: a change that
silently tanks in-domain accuracy, cross-domain accuracy, DA recovery,
NOTA F1, or adversarial robustness fails tier-1 before it ships.
Re-emitting the artifact (``python tools/scenarios.py --artifact
SCENARIOS_r<next>.json``) is the ONE sanctioned way to move the band.

With ``--run_dir`` every leg also lands as a ``kind="scenario"`` record
in metrics.jsonl (rendered by tools/obs_report.py's scenarios section,
validated by ``--check``).

Usage:
    python tools/scenarios.py [--artifact SCENARIOS_r01.json]
        [--mode full|tier1] [--seed 0] [--run_dir DIR]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from induction_network_on_fewrel_tpu.serving.geometry import (  # noqa: E402
    grid_key,
    parse_grid_key,
)

# The tier-1 regression band: one-sided quality floors (a LOWER number
# than recorded-minus-band fails; improvements never do). Abs tolerances
# sized to the miniature run's episode-sampling noise (~3 sigma of the
# observed acc_ci95) — the gate catches cliffs (broken routing, a loss
# regression, an episode-sampler bug), not weather.
TIER1_BAND = {
    "accuracy_abs": 0.12,
    "f1_abs": 0.15,
}

# Miniature (tier-1) scenario config: the smallest world where the
# trigger signal trains to well-above-chance in ~150 steps on CPU. CE
# loss on purpose — the MSE fixture's degenerate basin (test_train.py
# seed notes) is a loss pathology, not the quality signal this harness
# gates. seed=1 matches the NOTA overfit test's pinned rationale.
TIER1 = dict(
    num_relations=5, instances_per_relation=20, iters=150,
    eval_episodes=48, shifts=(1.0,), na_grid=(1,),
    adversarial=("token_noise:0.4", "blank:1.0"),
    # Miniature (N, K) eval grid (ISSUE 19): the paper's grid scaled to
    # the 5-relation world (10-way is unsamplable here; 5-way uses every
    # relation). Same trained params, fresh samplers per point.
    grid=((2, 1), (2, 2), (5, 1), (5, 2)),
    cfg=dict(
        model="induction", encoder="cnn", hidden_size=64,
        induction_dim=32, ntn_slices=32, routing_iters=2,
        train_n=2, n=2, k=2, q=2, na_rate=1, batch_size=4,
        max_length=16, vocab_size=302, word_dim=50,
        compute_dtype="float32", loss="ce", lr=5e-3,
        weight_decay=0.0, val_step=0, device="cpu", seed=1,
    ),
)

# Full-mode config: the 5-way 5-shot FewRel geometry on a larger
# synthetic corpus, a shift grid, an na_rate grid, and the mixture-ramp
# DA arm. Minutes on CPU — artifact generation, not tier-1.
FULL = dict(
    num_relations=10, instances_per_relation=20, iters=600,
    eval_episodes=160, shifts=(0.5, 1.0), na_grid=(1, 2),
    adversarial=(
        "token_noise:0.3", "token_noise:0.6", "mask_drop:0.5", "blank:1.0",
    ),
    # The paper's full eval grid (PAPER.md pillar 7): 5w1s and 10w5s
    # next to the 5w5s flagship, plus 10w1s — the hardest corner.
    grid=((5, 1), (5, 5), (10, 1), (10, 5)),
    cfg=dict(
        model="induction", encoder="cnn", hidden_size=64,
        induction_dim=32, ntn_slices=32, routing_iters=2,
        train_n=5, n=5, k=5, q=5, na_rate=1, batch_size=4,
        max_length=16, vocab_size=302, word_dim=50,
        compute_dtype="float32", loss="ce", lr=5e-3,
        weight_decay=0.0, val_step=0, device="cpu", seed=1,
    ),
)


def _world(plan: dict, seed: int):
    """(cfg, tokenizer, source ds, {shift: shifted ds}, glove vectors)."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_domain_shifted_fewrel,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )

    cfg = ExperimentConfig(**plan["cfg"])
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    src = make_synthetic_fewrel(
        num_relations=plan["num_relations"],
        instances_per_relation=plan["instances_per_relation"],
        vocab_size=cfg.vocab_size - 2, seed=seed,
    )
    tgts = {
        shift: make_domain_shifted_fewrel(
            num_relations=plan["num_relations"],
            instances_per_relation=plan["instances_per_relation"],
            vocab_size=cfg.vocab_size - 2, shift=shift, seed=seed,
        )
        for shift in plan["shifts"]
    }
    return cfg, tok, src, tgts, vocab


def _sampler(ds, tok, cfg, seed, na_rate=None):
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler

    return EpisodeSampler(
        ds, tok, n=cfg.n, k=cfg.k, q=cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate if na_rate is None else na_rate, seed=seed,
    )


def _train(cfg, vocab, sampler, iters):
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.train import FewShotTrainer
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    model = build_model(cfg, glove_init=vocab.vectors)
    trainer = FewShotTrainer(
        model, cfg, sampler, logger=MetricsLogger(quiet=True)
    )
    state = trainer.train(num_iters=iters)
    return model, trainer, state


def _eval_leg(trainer, params, sampler, episodes) -> dict:
    m = trainer.evaluate(
        params, num_episodes=episodes, sampler=sampler, return_metrics=True
    )
    out = {
        "accuracy": round(m["accuracy"], 4),
        "acc_ci95": round(m["acc_ci95"], 4),
    }
    for k in ("nota_precision", "nota_recall"):
        if k in m:
            out[k] = round(m[k], 4)
    return out


# --- NOTA threshold calibration -------------------------------------------


def nota_operating_points(gap, is_true_nota, taus) -> list[dict]:
    """Precision/recall/F1 per threshold bias tau.

    ``gap``: per-query (best class score − NOTA logit); the decision is
    NOTA iff ``nota_logit + tau > best`` ⇔ ``tau > gap``, so the
    predicted-NOTA set GROWS monotonically in tau — recall is
    nondecreasing, the predicted count nondecreasing (pinned in
    tests/test_scenarios.py). Convention at the empty end: precision 1.0
    with zero predictions (nothing asserted, nothing wrong)."""
    import numpy as np

    gap = np.asarray(gap, dtype=np.float64)
    truth = np.asarray(is_true_nota, dtype=bool)
    out = []
    for tau in taus:
        pred = gap < float(tau)
        tp = float(np.sum(pred & truth))
        n_pred = float(np.sum(pred))
        n_true = float(np.sum(truth))
        precision = tp / n_pred if n_pred else 1.0
        recall = tp / n_true if n_true else 0.0
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall > 0 else 0.0
        )
        out.append({
            "tau": round(float(tau), 4),
            "precision": round(precision, 4),
            "recall": round(recall, 4),
            "f1": round(f1, 4),
            "nota_rate": round(n_pred / max(len(gap), 1), 4),
        })
    return out


def default_tau_grid(gap, points: int = 13):
    """Quantile grid over the observed gap distribution (every tau is a
    real operating point), bracketed by all-NOTA / no-NOTA endpoints and
    always including 0.0 — the learned head's own calibration."""
    import numpy as np

    gap = np.asarray(gap, dtype=np.float64)
    qs = np.quantile(gap, np.linspace(0.02, 0.98, points))
    taus = sorted(set(
        [round(float(t), 4) for t in qs]
        + [0.0, round(float(gap.min()) - 1.0, 4),
           round(float(gap.max()) + 1.0, 4)]
    ))
    return taus


def nota_calibration(model, params, cfg, ds, tok, episodes, na_rate,
                     seed) -> dict:
    """Collect logits over NOTA-bearing eval episodes, sweep the
    threshold grid, pick best-F1, and record the quality baseline at
    that operating point (the drift detector's publish-time
    calibration)."""
    import jax
    import numpy as np

    from induction_network_on_fewrel_tpu.models.build import (
        batch_to_model_inputs,
    )
    from induction_network_on_fewrel_tpu.obs.drift import quality_features

    sampler = _sampler(ds, tok, cfg, seed=seed + 31, na_rate=na_rate)
    apply = jax.jit(lambda p, s, q: model.apply(p, s, q))
    rows, labels = [], []
    n_batches = max(1, episodes // cfg.batch_size)
    for _ in range(n_batches):
        sup, qry, lab = batch_to_model_inputs(sampler.sample_batch())
        logits = np.asarray(apply(params, sup, qry))   # [B, TQ, n+1]
        rows.append(logits.reshape(-1, logits.shape[-1]))
        labels.append(np.asarray(lab).reshape(-1))
    rows = np.concatenate(rows)
    labels = np.concatenate(labels)
    n = cfg.n
    best = rows[:, :n].max(axis=-1)
    gap = best - rows[:, n]
    truth = labels == n
    taus = default_tau_grid(gap)
    ops = nota_operating_points(gap, truth, taus)
    best_op = max(ops, key=lambda o: o["f1"])
    # Quality baseline AT the chosen operating point: what the drift
    # detector should consider "normal" for traffic like this eval's.
    margin, entropy = quality_features(rows[:, :n])
    pred = gap < best_op["tau"]
    baseline = {
        "nota_rate": [round(float(pred.mean()), 4),
                      round(float(pred.std()), 4)],
        "margin": [round(float(margin.mean()), 4),
                   round(float(margin.std()), 4)],
        "entropy": [round(float(entropy.mean()), 4),
                    round(float(entropy.std()), 4)],
    }
    return {
        "na_rate": na_rate,
        "queries": int(len(gap)),
        "operating_points": ops,
        "best": best_op,
        "baseline": baseline,
    }


# --- library-level canary gate (ISSUE 14 satellite) ------------------------
#
# The quality floors as a plan-in/verdict-out ENTRYPOINT — no argv, no
# main() coupling — so the adaptation controller's pre-publish canary
# (obs/adapt.py) and this CLI share ONE home for what "good enough to
# ship" means. A candidate that fails any floor is discarded by the
# controller, never published.


def floors_from_headline(headline: dict,
                         band: dict | None = None) -> dict[str, float]:
    """Turn a recorded tier1 headline (``tier1_headline``'s shape, e.g.
    the committed SCENARIOS artifact's ``tier1`` block) into canary
    floors: each accuracy minus the tier-1 band — the SAME one-sided
    bars tests/test_scenarios.py gates on."""
    tol = (band or TIER1_BAND)["accuracy_abs"]
    floors = {}
    for key in ("in_domain_accuracy", "cross_domain_accuracy",
                "da_mixture_accuracy"):
        if isinstance(headline.get(key), (int, float)):
            floors[key] = round(max(headline[key] - tol, 0.0), 4)
    # Per-geometry grid floors (ISSUE 19): one bar per recorded (N, K)
    # point, named grid_<N>w<K>s — run_canary parses the geometry back
    # out of the leg name. Headlines predating the grid produce none.
    for key, acc in (headline.get("grid") or {}).items():
        if isinstance(acc, (int, float)):
            floors[f"grid_{key}"] = round(max(acc - tol, 0.0), 4)
    return floors


def canary_verdict(legs: dict, floors: dict[str, float]) -> dict:
    """Hold evaluated legs to their floors. ``legs``: {name: {"accuracy":
    ...}} (extra legs without a floor are recorded, not judged; a floor
    without a matching leg FAILS — a gate that silently skips a bar is
    worse than no gate). Verdict: {"passed", "legs", "failures"}."""
    failures = []
    out_legs = {}
    for name, leg in legs.items():
        acc = leg.get("accuracy")
        floor = floors.get(name)
        row = {"accuracy": acc}
        if floor is not None:
            row["floor"] = floor
            row["ok"] = bool(acc is not None and acc >= floor)
            if not row["ok"]:
                failures.append(
                    f"{name}: accuracy {acc} below floor {floor}"
                )
        out_legs[name] = row
    for name in sorted(set(floors) - set(legs)):
        failures.append(f"{name}: floor {floors[name]} has no evaluated leg")
    return {"passed": not failures, "legs": out_legs, "failures": failures}


def run_canary(model, params, cfg, tok, legs: dict, floors: dict,
               episodes: int = 48, seed: int = 0) -> dict:
    """Evaluate candidate ``params`` on each leg's dataset and hold it
    to the floors. ``legs``: {name: FewRel-schema dataset} (episode
    geometry from ``cfg``); ``floors``: {name: min accuracy}. Returns
    the ``canary_verdict`` dict with per-leg accuracy/acc_ci95.

    Geometry legs (ISSUE 19): a leg named ``grid_<N>w<K>s`` (or bare
    ``<N>w<K>s``) is evaluated at THAT episode geometry —
    ``cfg.replace(n=N, k=K)`` — instead of ``cfg``'s. An adaptation
    candidate that recovers 5w5s but regresses 10w1s fails its grid
    floor and is never published."""
    from induction_network_on_fewrel_tpu.train import FewShotTrainer
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    if not legs:
        raise ValueError("canary needs at least one evaluation leg")
    first = next(iter(legs.values()))
    trainer = FewShotTrainer(
        model, cfg, _sampler(first, tok, cfg, seed=seed),
        logger=MetricsLogger(quiet=True),
    )
    try:
        evaluated = {}
        for i, (name, ds) in enumerate(sorted(legs.items())):
            geom = parse_grid_key(name)
            leg_cfg = (
                dataclasses.replace(cfg, n=geom[0], k=geom[1])
                if geom else cfg
            )
            evaluated[name] = _eval_leg(
                trainer, params,
                # Grid legs score plain N-way accuracy (na_rate=0, like
                # the scenario harness's grid): an all-relations N-way
                # point has no spare relation to draw NOTA from.
                _sampler(ds, tok, leg_cfg, seed=seed + 17 + i,
                         na_rate=0 if geom else None),
                episodes,
            )
    finally:
        trainer.close()
    return canary_verdict(evaluated, floors)


# --- the harness ----------------------------------------------------------


def run(plan: dict, seed: int, logger=None, step0: int = 0,
        tag: str = "") -> dict:
    """Run every scenario family under ``plan``; returns the result dict
    and (with ``logger``) emits one kind="scenario" record per leg.
    ``tag`` prefixes the emitted leg names — the full-mode artifact run
    emits its tier1 miniature with tag="tier1:" so the two configs'
    records never collide in one metrics.jsonl (obs_report's scenario
    table is last-record-wins per leg key)."""
    from induction_network_on_fewrel_tpu.datapipe.faults import (
        PerturbedSampler,
    )
    from induction_network_on_fewrel_tpu.datapipe.mixture import (
        MixtureSampler,
        MixtureSchedule,
    )

    t0 = time.monotonic()
    cfg, tok, src, tgts, vocab = _world(plan, seed)
    step = step0

    def emit(leg: str, fields: dict) -> None:
        nonlocal step
        if logger is not None:
            scalars = {
                k: v for k, v in fields.items()
                if isinstance(v, (int, float, str))
            }
            logger.log(step, kind="scenario", leg=tag + leg, **scalars)
        step += 1

    # -- source-domain training + cross-domain evals -----------------------
    model, trainer, state = _train(
        cfg, vocab, _sampler(src, tok, cfg, seed=seed + 1), plan["iters"]
    )
    in_domain = _eval_leg(
        trainer, state.params, _sampler(src, tok, cfg, seed=seed + 2),
        plan["eval_episodes"],
    )
    emit("in_domain", in_domain)
    cross = {}
    for shift, tgt in sorted(tgts.items()):
        r = _eval_leg(
            trainer, state.params, _sampler(tgt, tok, cfg, seed=seed + 3),
            plan["eval_episodes"],
        )
        r["shift"] = shift
        cross[f"{shift:g}"] = r
        emit("cross_domain", r)

    # -- DA arm: train THROUGH the mixture machinery -----------------------
    # The FewRel 2.0 curriculum spelling: source at weight 1.0, the
    # hardest shifted twin ramping in over the first 60% of training
    # (weights move, episode geometry doesn't — static shapes).
    hardest = max(tgts)
    ramp_at = max(int(plan["iters"] * 0.6), 1)
    schedule = MixtureSchedule.parse(
        f"src:1.0;tgt:0.2@0,1.0@{ramp_at}"
    )
    mix = MixtureSampler(
        [("src", _sampler(src, tok, cfg, seed=seed + 5)),
         ("tgt", _sampler(tgts[hardest], tok, cfg, seed=seed + 6))],
        schedule, seed=seed,
    )
    _, da_trainer, da_state = _train(cfg, vocab, mix, plan["iters"])
    da = _eval_leg(
        da_trainer, da_state.params,
        _sampler(tgts[hardest], tok, cfg, seed=seed + 3),
        plan["eval_episodes"],
    )
    da["shift"] = hardest
    da["schedule"] = schedule.to_spec()
    da["mixture_counts"] = dict(mix.counts)
    emit("da_mixture", {k: v for k, v in da.items()
                        if not isinstance(v, dict)})

    # -- NOTA threshold calibration ----------------------------------------
    nota = {}
    for na in plan["na_grid"]:
        r = nota_calibration(
            model, state.params, cfg, src, tok, plan["eval_episodes"],
            na_rate=na, seed=seed,
        )
        nota[str(na)] = r
        emit("nota_calibration", {
            "na_rate": float(na), "queries": float(r["queries"]),
            "best_tau": r["best"]["tau"], "best_f1": r["best"]["f1"],
            "best_precision": r["best"]["precision"],
            "best_recall": r["best"]["recall"],
            "baseline_nota_rate": r["baseline"]["nota_rate"][0],
            "baseline_margin": r["baseline"]["margin"][0],
            "baseline_entropy": r["baseline"]["entropy"][0],
        })

    # -- adversarial / noisy episode legs ----------------------------------
    adversarial = {"clean": in_domain}
    for spec in plan["adversarial"]:
        r = _eval_leg(
            trainer, state.params,
            PerturbedSampler(
                _sampler(src, tok, cfg, seed=seed + 2), spec, seed=seed + 9
            ),
            plan["eval_episodes"],
        )
        r["degradation"] = round(in_domain["accuracy"] - r["accuracy"], 4)
        adversarial[spec] = r
        emit(spec, r)

    # -- (N, K) eval grid (ISSUE 19) ---------------------------------------
    # The paper's episode-geometry grid on the SAME trained params: each
    # point re-samples source episodes at (n, k) and reports accuracy +
    # acc_ci95. Appended after every pre-existing leg with fresh seed
    # offsets so the committed artifact's earlier numbers replay
    # byte-identically; jit retraces per episode shape, so each point is
    # one extra compile, not a config change.
    grid = {}
    for i, (gn, gk) in enumerate(plan.get("grid", ())):
        gcfg = dataclasses.replace(cfg, n=gn, k=gk)
        # na_rate=0: the paper grid is plain N-way accuracy, and the
        # N-way-over-all-relations points could not sample a NOTA
        # distractor relation anyway (needs N+1).
        r = _eval_leg(
            trainer, state.params,
            _sampler(src, tok, gcfg, seed=seed + 400 + i, na_rate=0),
            plan["eval_episodes"],
        )
        r["n"], r["k"] = gn, gk
        key = grid_key(gn, gk)
        grid[key] = r
        emit(f"grid_{key}", r)

    cross_worst = min(c["accuracy"] for c in cross.values())
    return {
        "config": dict(plan["cfg"]),
        "seed": seed,
        "iters": plan["iters"],
        "eval_episodes": plan["eval_episodes"],
        "wall_s": round(time.monotonic() - t0, 1),
        "cross_domain": {
            "in_domain": in_domain,
            "by_shift": cross,
            "gap_at_worst_shift": round(
                in_domain["accuracy"] - cross_worst, 4
            ),
            "da_mixture": da,
        },
        "nota": nota,
        "adversarial": adversarial,
        "grid": grid,
    }


def run_tier1(seed: int = 1, logger=None, tag: str = "") -> dict:
    """The miniature leg: what tests/test_scenarios.py replays in-process
    against the committed SCENARIOS artifact, and what bench.py stamps.
    Deterministic under a fixed seed on a fixed stack."""
    return run(TIER1, seed=seed, logger=logger, tag=tag)


def tier1_headline(res: dict) -> dict:
    """The gated numbers, flat — the artifact's ``tier1`` block."""
    # key=float: the dict keys are stringified numbers, and lexicographic
    # max/min would pick the wrong leg on grids like ("0.5", "1e-05") or
    # na rates ("2", "10").
    hardest = max(res["cross_domain"]["by_shift"], key=float)
    na0 = min(res["nota"], key=float)
    adv = {
        spec: leg["accuracy"]
        for spec, leg in res["adversarial"].items() if spec != "clean"
    }
    return {
        "seed": res["seed"],
        "in_domain_accuracy": res["cross_domain"]["in_domain"]["accuracy"],
        "cross_domain_accuracy":
            res["cross_domain"]["by_shift"][hardest]["accuracy"],
        "da_mixture_accuracy": res["cross_domain"]["da_mixture"]["accuracy"],
        "nota_best_f1": res["nota"][na0]["best"]["f1"],
        "adversarial_accuracy": adv,
        # Per-(N, K) grid accuracies (ISSUE 19) — canary floors derive
        # grid_<key> bars from these, so an adaptation that recovers the
        # flagship geometry but regresses another grid point cannot ship.
        "grid": {
            key: leg["accuracy"]
            for key, leg in res.get("grid", {}).items()
        },
        "band": dict(TIER1_BAND),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="model-quality scenario harness (DA + NOTA + noise)"
    )
    ap.add_argument("--artifact", default=None, metavar="PATH",
                    help="write SCENARIOS_r*.json here (full + tier1)")
    ap.add_argument("--mode", default="full", choices=["full", "tier1"],
                    help="tier1 = the miniature gate leg only")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--run_dir", default=None,
                    help="also emit kind='scenario' records to this dir's "
                         "metrics.jsonl (tools/obs_report.py renders them)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    logger = None
    if args.run_dir:
        from induction_network_on_fewrel_tpu.utils.metrics import (
            MetricsLogger,
        )

        logger = MetricsLogger(args.run_dir)

    try:
        if args.mode == "tier1":
            res = run_tier1(seed=args.seed, logger=logger)
            report = {"tier1_run": res, "tier1": tier1_headline(res)}
        else:
            print("scenarios: full mode (DA grid + na grid + mixture arm)",
                  file=sys.stderr)
            full = run(FULL, seed=args.seed, logger=logger)
            print(f"scenarios: full done in {full['wall_s']}s; tier1 leg...",
                  file=sys.stderr)
            # tier1: tagged leg names, so the miniature config's records
            # never overwrite the full-mode rows in one metrics.jsonl.
            t1 = run_tier1(seed=args.seed, logger=logger, tag="tier1:")
            report = {
                "round": 1,
                "generated_by": "tools/scenarios.py",
                "generated_unix_s": int(time.time()),
                "full": full,
                "tier1_run": t1,
                "tier1": tier1_headline(t1),
            }
        print(json.dumps(report.get("tier1", report), indent=1))
        if args.artifact:
            with open(args.artifact, "w") as f:
                json.dump(report, f, indent=1)
            print(f"wrote {args.artifact}", file=sys.stderr)
        return 0
    finally:
        if logger is not None:
            logger.close()


if __name__ == "__main__":
    sys.exit(main())
