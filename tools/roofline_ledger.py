#!/usr/bin/env python3
"""Flagship roofline ledger (round-5 VERDICT item 3): predicted step-time
floor from per-component HBM bytes + MXU FLOPs vs the measured step time.

Three parts, all measured/derived on THIS chip in one run:

1. **Calibration** — effective HBM bandwidth (IN-JIT streaming loop: 50
   iterations of a 3-array f32 saxpy inside one compiled program) and
   effective MXU throughput (serialized bf16 4096^2 matmul chain). The
   sandbox v5e behind the axon tunnel delivers a fraction of nominal
   (measured round 5: ~284-297 GB/s of 819, ~80-91 TFLOP/s of 197) —
   the ledger uses the MEASURED numbers, so the prediction targets this
   chip, then projects to production silicon. (A per-dispatch probe
   reads only ~65 GB/s — that is tunnel launch gap, NOT HBM; see
   calibrate() — and must never be used as a denominator.)
2. **Analytic ledger** — per-component bytes and FLOPs for one training
   step of the flagship config (B=64 5w5s, bilstm L=40, token-cache lazy).
   The formulas live in utils/roofline.py (shared with bench.py's
   ``step_bytes`` field); component time floor =
   max(bytes / BW, flops / MXU)  (bandwidth- and compute-bound phases
   cannot overlap below this). Round 6 prints BOTH attention-residual
   policies (remat_attn on/off) so the byte diet is an explicit A/B.
3. **Measurement** — one hard-synced fused call of the real production
   step (bench.py machinery) -> measured ms/step to compare.

Usage:  python tools/roofline_ledger.py [--spc 256] [--skip-measure]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# One home (ISSUE 11): the nominal v5e constants moved next to the
# component formulas so the online perf observer projects the same floor.
from induction_network_on_fewrel_tpu.utils.roofline import (  # noqa: E402
    NOMINAL_V5E_BW as NOMINAL_BW,
    NOMINAL_V5E_MXU as NOMINAL_MXU,
)


def calibrate(jax):
    import numpy as np

    jnp = jax.numpy
    n = 64 * 1024 * 1024
    x = jnp.ones((n,), jnp.float32)
    # IN-JIT loop (one dispatch, 50 iterations of z = z*c + x, 3 arrays of
    # HBM traffic each): measures the bandwidth a compiled program's
    # interior actually gets. A per-dispatch probe on this tunneled
    # backend reads ~65 GB/s — that is queue/launch gap, not HBM (measured
    # round 5: in-jit 295 GB/s vs dispatch-level 65); step-internal
    # accounting must use the in-jit number.
    f = jax.jit(lambda z: jax.lax.scan(
        lambda z, _: (z * 0.999 + x, None), z, None, length=50)[0])
    z = f(x)
    _ = float(z[0])
    t0 = time.monotonic()
    z = f(z)
    _ = float(z[0])
    bw = 3 * n * 4 * 50 / (time.monotonic() - t0)

    k, iters = 4096, 100
    a = (jax.random.normal(jax.random.key(0), (k, k), jnp.float32)
         / np.sqrt(k)).astype(jnp.bfloat16)
    mm = jax.jit(lambda c: jax.lax.scan(
        lambda c, _: ((a @ c).astype(jnp.bfloat16), None), c, None,
        length=iters)[0])
    c = mm(jnp.eye(k, dtype=jnp.bfloat16))
    _ = float(c[0, 0])
    t0 = time.monotonic()
    c = mm(c)
    _ = float(c[0, 0])
    mxu = 2 * k**3 * iters / (time.monotonic() - t0)
    return bw, mxu


def ledger(
    cfg,
    remat_attn: bool | None = None,
    lstm_cs_window: int | None = None,
    lstm_residuals: str | None = None,
) -> list[tuple[str, float, float]]:
    """[(component, bytes/step, flops/step)] for the flagship train step.

    The formulas live in utils/roofline.py (round 6: bench.py stamps
    ``step_bytes`` from the same arithmetic). ``remat_attn`` selects the
    attention-residual policy, ``lstm_cs_window``/``lstm_residuals`` the
    round-8 BiLSTM residual policy; None follows the config.
    """
    from induction_network_on_fewrel_tpu.utils.roofline import step_components

    return step_components(
        cfg, remat_attn,
        lstm_cs_window=lstm_cs_window, lstm_residuals=lstm_residuals,
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--spc", type=int, default=256)
    ap.add_argument("--skip-measure", action="store_true")
    ap.add_argument("--json", default=None)
    ap.add_argument(
        "--remat", default="on", choices=["on", "off"],
        help="attention-residual policy for the PRODUCTION rows "
             "(the tool always prints both for the A/B)",
    )
    ap.add_argument(
        "--cs_window", type=int, default=8,
        help="BiLSTM windowed-cs remat window for the PRODUCTION rows "
             "(round 8; 0 = full-cs residuals — the tool always prints "
             "the full-cs twin for the A/B)",
    )
    ap.add_argument(
        "--residuals", default="auto", choices=["auto", "f32", "bf16"],
        help="BiLSTM residual storage dtype (auto = follow compute dtype)",
    )
    args = ap.parse_args()

    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    remat = args.remat == "on"
    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=64, max_length=40,
        vocab_size=400002, compute_dtype="bfloat16",
        steps_per_call=args.spc, token_cache=True, embed_optimizer="lazy",
        remat_attn=remat, lstm_cs_window=args.cs_window,
        lstm_residuals=args.residuals,
    )

    bw, mxu = calibrate(jax)
    print(f"calibrated: HBM {bw / 1e9:.1f} GB/s "
          f"({bw / NOMINAL_BW:.1%} of nominal), "
          f"MXU {mxu / 1e12:.1f} TFLOP/s ({mxu / NOMINAL_MXU:.1%})")

    # The A/B ladder, one round per rung: round-5 policy (no attn remat,
    # full-cs residuals), round-6/7 (attn remat, full-cs), round-8 (attn
    # remat + windowed-cs checkpoints at the configured window/dtype).
    policies = [
        ("remat_attn OFF, full-cs (round-5 policy)",
         dict(remat_attn=False, lstm_cs_window=0)),
        ("remat_attn ON, full-cs (round-6/7 policy)",
         dict(remat_attn=True, lstm_cs_window=0)),
        (f"remat_attn ON, windowed-cs W={args.cs_window} "
         f"residuals={args.residuals} (round-8 policy)",
         dict(remat_attn=True)),
    ]
    totals = {}
    for tag, kw in policies:
        rows = ledger(cfg, **kw)
        total_b = sum(r[1] for r in rows)
        total_f = sum(r[2] for r in rows)
        print(f"\n=== {tag} ===")
        print(f"{'component':45s} {'MB/step':>8s} {'GFLOP':>7s} "
              f"{'t_bw ms':>8s} {'t_mxu ms':>8s} {'floor ms':>8s}")
        floor = 0.0
        for name, b, f in rows:
            tb, tf = b / bw * 1e3, f / mxu * 1e3
            floor += max(tb, tf)
            print(f"{name:45s} {b / 1e6:8.1f} {f / 1e9:7.1f} "
                  f"{tb:8.3f} {tf:8.3f} {max(tb, tf):8.3f}")
        print(f"{'TOTAL':45s} {total_b / 1e6:8.1f} {total_f / 1e9:7.1f} "
              f"{'':8s} {'':8s} {floor:8.3f}")
        totals[tag] = total_b

    # Production rows follow the CONFIG (the cli-shaped knobs) — the floor
    # is computed from THESE rows directly, not looked up in the ladder:
    # cross combinations (--remat off with a window, say) are not ladder
    # rungs and a rung lookup would stamp an inconsistent artifact.
    from induction_network_on_fewrel_tpu.utils.roofline import (
        projected_floor_ms,
    )

    rows = ledger(cfg)
    floor = projected_floor_ms(cfg, bw=bw, mxu=mxu)
    t5, t6, t8 = (totals[t] for t, _ in policies)
    print(f"\nbyte diet: {t5 / 1e6:.1f} -> {t6 / 1e6:.1f} -> {t8 / 1e6:.1f} "
          f"MB/step (round-5 -> attn remat -> + windowed-cs; "
          f"{t8 / t6:.1%} of round-6)")

    # Production-silicon projection at nominal BW/MXU — the SAME helper
    # the online perf observer stamps into kind="perf" (one spelling).
    floor_prod = projected_floor_ms(cfg)
    eps_prod = cfg.batch_size / (floor_prod / 1e3)
    print(f"projected floor on nominal v5e (819 GB/s, 197 TF/s): "
          f"{floor_prod:.3f} ms/step -> {eps_prod:,.0f} eps/s/chip ceiling")

    measured = None
    if not args.skip_measure:
        print("\nmeasuring one fused call of the production step...")
        from induction_network_on_fewrel_tpu.data import (
            GloveTokenizer,
            make_synthetic_fewrel,
            make_synthetic_glove,
        )
        from induction_network_on_fewrel_tpu.models import build_model
        from induction_network_on_fewrel_tpu.native.sampler import (
            make_index_sampler,
        )
        from induction_network_on_fewrel_tpu.train.lazy_embed import (
            augment_token_table,
        )
        from induction_network_on_fewrel_tpu.train.steps import init_state
        from induction_network_on_fewrel_tpu.train.token_cache import (
            make_token_cached_multi_train_step,
            tokenize_dataset,
        )

        vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
        ds = make_synthetic_fewrel(
            num_relations=20, instances_per_relation=cfg.k + cfg.q + 5,
            vocab_size=min(cfg.vocab_size - 2, 2000),
        )
        tok = GloveTokenizer(vocab, max_length=cfg.max_length)
        table_np, sizes = tokenize_dataset(ds, tok)
        table_np, uids = augment_token_table(table_np)
        table_np = {**table_np, "uids": uids}
        table = jax.device_put(table_np)
        sampler = make_index_sampler(
            sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0
        )
        model = build_model(cfg, glove_init=vocab.vectors)
        b0s, b0q, _ = sampler.sample_fused(1)
        sup = {k: v[b0s[0]] for k, v in table_np.items() if k != "uids"}
        qry = {k: v[b0q[0]] for k, v in table_np.items() if k != "uids"}
        state = init_state(model, cfg, sup, qry)
        multi = make_token_cached_multi_train_step(model, cfg)

        def call(state):
            si, qi, lab = sampler.sample_fused(args.spc)
            return multi(state, table, si, qi, lab)

        for _ in range(2):
            state, m = call(state)
        _ = float(jax.device_get(m["loss"])[-1])
        best = None
        for _ in range(3):
            t0 = time.monotonic()
            state, m = call(state)
            _ = float(jax.device_get(m["loss"])[-1])
            dt = time.monotonic() - t0
            best = dt if best is None else min(best, dt)
        sampler.close()
        measured = best / args.spc * 1e3
        print(f"measured: {best:.3f} s/call -> {measured:.3f} ms/step "
              f"({cfg.batch_size / (best / args.spc):,.0f} eps/s/chip); "
              f"predicted floor {floor:.3f} ms/step "
              f"-> floor/measured = {floor / measured:.1%}")

    if args.json:
        from induction_network_on_fewrel_tpu.utils.roofline import (
            lstm_residual_bytes,
        )

        with open(args.json, "w") as f:
            json.dump({
                # Calibration backend matters: CPU-emitted ledgers carry
                # honest-but-irrelevant bw/mxu floors; the component BYTE
                # rows are analytic and backend-independent.
                "calibration_backend": __import__("jax").default_backend(),
                "calibrated_bw_GBs": round(bw / 1e9, 1),
                "calibrated_mxu_TFs": round(mxu / 1e12, 1),
                "remat_attn": remat,
                "lstm_cs_window": args.cs_window,
                "lstm_residuals": args.residuals,
                "components": [
                    {"name": n, "bytes": b, "flops": fl}
                    for n, b, fl in rows
                ],
                # The A/B ladder totals (round-5 -> round-6/7 -> round-8
                # policies); "step_bytes" is the PRODUCTION config's total
                # — the value the tier-1 regression gate holds
                # (tests/test_roofline.py: step_bytes <= recorded + 2%).
                "step_bytes": int(sum(b for _, b, _ in rows)),
                "step_bytes_full_cs": int(totals[policies[1][0]]),
                "step_bytes_no_remat": int(totals[policies[0][0]]),
                "lstm_residual_bytes": int(lstm_residual_bytes(cfg)),
                "floor_ms_this_chip": round(floor, 3),
                "floor_ms_nominal_v5e": round(floor_prod, 3),
                "measured_ms_per_step": (
                    round(measured, 3) if measured else None
                ),
            }, f, indent=1)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
