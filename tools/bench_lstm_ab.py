#!/usr/bin/env python3
"""Interleaved A/B bench: scan vs pallas BiLSTM, end-to-end train steps.

The axon tunnel's latency drifts by orders of magnitude within a session, so
back-to-back runs of two variants confound backend choice with tunnel state.
This script builds BOTH train steps in one process and alternates chunks
A,B,A,B,... so drift hits both arms equally; reports per-arm best and median
chunk rates.

Usage: python tools/bench_lstm_ab.py [rounds] [chunk_steps]
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BATCH = 8
ROUNDS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
CHUNK = int(sys.argv[2]) if len(sys.argv) > 2 else 20


def build_arm(lstm_backend: str):
    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
    from induction_network_on_fewrel_tpu.native import make_sampler
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=BATCH, max_length=40,
        vocab_size=2002, compute_dtype="bfloat16", lstm_backend=lstm_backend,
    )
    ds = make_synthetic_fewrel(
        num_relations=20, instances_per_relation=cfg.k + cfg.q + 5,
        vocab_size=cfg.vocab_size - 2,
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = make_sampler(
        ds, tok, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
        seed=0, backend="auto", prefetch=16, num_threads=4,
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)

    def step_once(st):
        return step(st, *batch_to_model_inputs(sampler.sample_batch()))

    return {"name": lstm_backend, "state": state, "step": step_once,
            "sampler": sampler, "rates": []}


def main() -> int:
    import jax

    from bench import _probe_tpu

    if not _probe_tpu():
        # The compiled pallas arm only lowers on a real TPU, and a
        # scan-vs-pallas A/B is meaningless on CPU — bail out cleanly.
        print("bench_lstm_ab: TPU backend unreachable; aborting (A/B needs "
              "the real chip)", file=sys.stderr)
        return 1

    def hard_sync(m):
        # Value fetch, not block_until_ready (bench.py docstring: block does
        # not actually wait on this tunneled backend).
        import numpy as np

        _ = float(np.ravel(jax.device_get(m["loss"]))[-1])

    arms = [build_arm("scan"), build_arm("pallas")]
    # warmup/compile both
    for arm in arms:
        t0 = time.monotonic()
        for _ in range(5):
            arm["state"], m = arm["step"](arm["state"])
        hard_sync(m)
        print(f"# {arm['name']}: compiled in {time.monotonic()-t0:.1f}s",
              file=sys.stderr)

    for r in range(ROUNDS):
        for arm in arms:
            t0 = time.monotonic()
            for _ in range(CHUNK):
                arm["state"], m = arm["step"](arm["state"])
            hard_sync(m)
            n_chips = max(jax.local_device_count(), 1)
            arm["rates"].append(CHUNK * BATCH / (time.monotonic() - t0) / n_chips)

    for arm in arms:
        print(json.dumps({
            "lstm_backend": arm["name"],
            "best_eps": round(max(arm["rates"]), 1),
            "median_eps": round(statistics.median(arm["rates"]), 1),
            "rates": [round(x, 1) for x in arm["rates"]],
            "backend": jax.default_backend(),
        }), flush=True)
        if hasattr(arm["sampler"], "close"):
            arm["sampler"].close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
