#!/usr/bin/env python3
"""Regression-gated bench trajectory: every committed perf artifact folded
into ONE timeseries, with per-metric bands a fresh leg must stay inside.

ISSUE 11 tentpole, layer 3. The repo carries five BENCH_r*.json, three
ROOFLINE_r*.json, five COMMS_r*.json and two SERVE_r*.json — disconnected
snapshots nobody reads side by side, so a perf regression is invisible
until someone rereads old JSON by hand. This tool makes the trajectory a
first-class artifact:

* default            — fold every committed artifact (plus the live
                       ``TREND_INPUT.jsonl`` rows bench.py appends per
                       run) into ``TREND.json`` and write it.
* ``--check``        — regenerate in memory and FAIL (exit 1) when (a)
                       any committed artifact contributed zero points
                       (the trajectory silently lost an input), (b) the
                       committed TREND.json is stale (regeneration
                       differs — new artifacts MUST re-run this tool),
                       or (c) the newest point of any banded series sits
                       outside the band its predecessors establish.
                       Runs in tier-1 (tests/test_bench_trend.py), so
                       the trajectory can never be empty or silently
                       regress again.
* ``--candidate F``  — additionally validate a fresh bench summary (the
                       one-line JSON bench.py prints, or a file holding
                       it) against the committed bands WITHOUT requiring
                       it to be committed first — the pre-commit gate
                       for a new bench leg.

Series keying — like-for-like only: throughput series are keyed by the
FULL bench metric string (the ``[5w5s,bilstm,...,vocab400002,B64,spc512,
embed_lazy,hardsync]`` bracket), the same discipline as bench.py's
per-config baseline dict: r02's full-vocab dense-Adam number must never
sit in one band with r01's small-vocab number. Bands therefore only bind
within a series holding >= 2 points of the SAME configuration.

Band rules (direction-aware, tolerances stated in BANDS):

* ``higher`` — newest >= (1 - tol) * best(previous). Throughput/MFU;
  tol 0.35 covers the documented ±30% tunnel weather (BASELINE.md).
* ``lower``  — newest <= (1 + tol) * best(previous) (best = min).
  Byte diets; tol matches the tier-1 roofline gate's +2%.
* ``floor``  — newest >= tol (an absolute floor; the scheduler-A/B qps
  ratio must stay >= 1.0 — ratios are the stable signal, absolute qps
  swings ~2x with sandbox neighbor load, BASELINE round 9).
* ``zero``   — newest must be 0 (unattributed collective bytes on the
  flagship leg; steady recompiles).

Usage:
    python tools/bench_trend.py [--root DIR] [--check] [--candidate F]
        [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent

TREND_NAME = "TREND.json"
LIVE_NAME = "TREND_INPUT.jsonl"

# series -> (rule, tolerance). Series not listed are recorded in the
# trajectory but never gated (e.g. absolute serving qps: honest numbers,
# documented-unstable on this sandbox).
BANDS: dict[str, tuple[str, float]] = {
    # Per-config throughput/MFU (keyed by the full metric bracket at
    # build time — see _bench_points): the two entries below are PREFIX
    # rules applied to every config-keyed series of that family.
    "bench.eps_per_s[": ("higher", 0.35),
    "bench.mfu[": ("higher", 0.35),
    "bench.step_ms[": ("lower", 0.55),   # 1/eps at the band's tolerance
    # Analytic byte diets: monotone by construction; the +2% matches
    # tests/test_roofline.py's artifact gate.
    "roofline.step_bytes": ("lower", 0.02),
    "roofline.step_bytes_no_remat": ("lower", 0.02),
    # floor_ms_nominal_v5e is recorded but NOT banded: remat designs
    # legitimately trade recompute FLOPs for bytes (the round-8
    # windowed-cs kernel RAISED the compute floor 1.349 -> 1.599 ms while
    # cutting step bytes 21% — an accepted tradeoff this tool's first run
    # flagged). step_bytes is the gated diet headline.
    # Comms: the flagship leg is the headline; unattributed bytes on it
    # must stay zero (the round-7 ledger discipline).
    "comms.flagship_payload_bytes": ("lower", 0.15),
    "comms.flagship_unattributed_bytes": ("zero", 0.0),
    "comms.dp8_lazy_payload_bytes": ("lower", 0.15),
    # Round 10: the measured whole-step overlap headline (ledger dataflow
    # windows, wire-weighted). Floor mirrors check_flagship's <= 8%
    # un-overlapped acceptance; wire bytes recorded unbanded (the ring
    # factor makes them a deterministic function of the payload diet).
    "comms.flagship_overlap_frac": ("floor", 0.92),
    "comms.dp8_lazy_bucketed_payload_bytes": ("lower", 0.15),
    # Serving: the scheduler-A/B ratio plus the hot-swap drill's zero-
    # drop invariant (absolute qps/p99 recorded, not gated).
    "serve.closed_qps_ratio": ("floor", 1.0),
    "serve.drill_dropped.continuous": ("zero", 0.0),
    "serve.drill_dropped.microbatch": ("zero", 0.0),
    "serve.drill_rejected.continuous": ("zero", 0.0),
    "serve.drill_rejected.microbatch": ("zero", 0.0),
    # Chaos drill (ISSUE 12, CHAOS_r*.json): the containment invariants
    # as zero-bands — a publish rollback must drop nothing and recompile
    # nothing — plus pass/recovery floors. A containment regression
    # fails --check the moment a new artifact records it.
    "chaos.dropped_during_rollback": ("zero", 0.0),
    "chaos.steady_recompiles": ("zero", 0.0),
    "chaos.passed": ("floor", 1.0),
    "chaos.ckpt_bitwise_recovery": ("floor", 1.0),
    "chaos.breaker_open_criticals": ("floor", 1.0),
    # Fleet soak (ISSUE 13, FLEET_r*.json): the router-tier containment
    # invariants as zero-bands — failover must drop nothing (degraded
    # verdicts are answers, not drops) and steady-state traffic across
    # every replica must compile nothing — plus the drill pass/recovery
    # floors. Absolute qps/p99 are recorded unbanded (documented-unstable
    # sandbox, same policy as serve.*).
    "fleet.dropped_during_failover": ("zero", 0.0),
    "fleet.steady_recompiles": ("zero", 0.0),
    "fleet.passed": ("floor", 1.0),
    "fleet.kill_recovered": ("floor", 1.0),
    # Adaptation drill (ISSUE 14, ADAPT_r*.json): the self-healing loop's
    # containment invariants as zero-bands — the fan-out publish of a
    # canary-passed candidate drops nothing and recompiles nothing, and
    # the FAILURE arm (forced canary fail) publishes NOTHING — plus the
    # recovery floor (the success arm must end with the tenant's NOTA
    # rate back in band and the detector re-armed). Wall times
    # (finetune_s / publish_s / recover_s) are recorded unbanded
    # (documented-unstable sandbox, same policy as serve.*).
    "adapt.dropped_during_publish": ("zero", 0.0),
    "adapt.steady_recompiles": ("zero", 0.0),
    "adapt.unexpected_publishes": ("zero", 0.0),
    "adapt.passed": ("floor", 1.0),
    "adapt.recovered": ("floor", 1.0),
    "adapt.exhausted_latched": ("floor", 1.0),
    # Recovery drill (ISSUE 15, RECOVERY_r*.json): the durability
    # invariants as zero-bands — a router kill/restart loses no tenant,
    # a supervised replica catch-up drops nothing and recompiles
    # nothing — plus the pass/bitwise-directory floors. Journal/restart
    # counts are recorded unbanded.
    "recovery.tenants_lost": ("zero", 0.0),
    "recovery.steady_recompiles": ("zero", 0.0),
    "recovery.dropped_during_catchup": ("zero", 0.0),
    "recovery.passed": ("floor", 1.0),
    "recovery.directory_bitwise": ("floor", 1.0),
    "recovery.placement_identical": ("floor", 1.0),
    "recovery.torn_prefix_recovered": ("floor", 1.0),
    # Elasticity drill (ISSUE 16, ELASTIC_r*.json): scaling must be
    # free — a scale-out/drain-in cycle drops nothing and recompiles
    # nothing in steady state, a standby promotion loses no tenant —
    # plus the pass/promotion floors. Warm/tick/tail counts are
    # recorded unbanded.
    "scale.dropped_during_scale": ("zero", 0.0),
    "scale.dropped_during_promotion": ("zero", 0.0),
    "scale.tenants_lost": ("zero", 0.0),
    "scale.steady_recompiles": ("zero", 0.0),
    "scale.passed": ("floor", 1.0),
    "scale.promotion_recovered": ("floor", 1.0),
    "scale.split_brain_refused": ("floor", 1.0),
    # Fleet observability drill (ISSUE 17, OBSFLEET_r*.json): the
    # stitching invariants as zero-bands — every sampled hop must find
    # its replica-side trace (unstitched_frac=0) and no replica trace
    # may go unclaimed (orphan_spans=0) — plus the pass/ordering
    # floors. Hop-tax latencies are recorded unbanded (documented-
    # unstable sandbox, same policy as serve.*).
    "obsfleet.orphan_spans": ("zero", 0.0),
    "obsfleet.unstitched_frac": ("zero", 0.0),
    "obsfleet.passed": ("floor", 1.0),
    "obsfleet.stitch_coverage": ("floor", 1.0),
    "obsfleet.incidents_ordered": ("floor", 1.0),
    # Quantized serving A/B (ISSUE 18, QUANT_r*.json): the density
    # regression gates — quantized arms must drop nothing and recompile
    # nothing (the zero-recompile gate holds per resident dtype), the
    # sampled shadow-vs-f32 verdict agreement has a hard floor, and the
    # f32/int8 resident-bytes ratio (the tenant-density headline) must
    # not erode. Absolute qps/p99 recorded unbanded (documented-unstable
    # sandbox, same policy as serve.*); tenants-per-chip is a labeled
    # CPU projection, recorded for the ratio trajectory only.
    "quant.dropped": ("zero", 0.0),
    "quant.steady_recompiles": ("zero", 0.0),
    "quant.passed": ("floor", 1.0),
    "quant.agreement.bf16": ("floor", 0.99),
    "quant.agreement.int8": ("floor", 0.99),
    "quant.bytes_ratio_f32_over_int8": ("floor", 3.5),
    # Mixed-geometry A/B (ISSUE 19, GEOM_r*.json): the tiered serving
    # arm must drop nothing and recompile nothing through a tier-
    # crossing re-registration AND a resident-dtype flip (the exact-N
    # arm's recompile tax is recorded unbanded — it's the documented
    # cost the tiers remove), and the per-(N, K) scenario-grid
    # accuracies are banded per point via the prefix rule below (same
    # episode-sampling tolerance as the scenario harness's tier-1
    # band). Absolute qps/p99 recorded unbanded (sandbox policy).
    "geom.tiered_dropped": ("zero", 0.0),
    "geom.tiered_steady_recompiles": ("zero", 0.0),
    "geom.steady_recompiles.tiered": ("zero", 0.0),
    "geom.passed": ("floor", 1.0),
    "geom.program_ratio_exact_over_tiered": ("floor", 1.0),
    "geom.grid_acc.": ("higher", 0.15),
}


def _band_rule(series: str) -> tuple[str, float] | None:
    if series in BANDS:
        return BANDS[series]
    for prefix, rule in BANDS.items():
        # Keys ending in "[" (config-bracket families) or "." (dotted
        # families like geom.grid_acc.<N>w<K>s) are PREFIX rules.
        if prefix.endswith(("[", ".")) and series.startswith(prefix):
            return rule
    return None


# --- extraction -----------------------------------------------------------

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _round_of(path: str) -> int | None:
    m = _ROUND_RE.search(path)
    return int(m.group(1)) if m else None


def _point(points: dict, series: str, rnd, source: str, value) -> None:
    if value is None or not isinstance(value, (int, float)):
        return
    points.setdefault(series, []).append({
        "round": rnd, "source": source, "value": value,
    })


_B_RE = re.compile(r"[\[,]B(\d+)[,\]]")


def _bench_points(points: dict, path: str, data: dict) -> int:
    """BENCH_r*.json: the driver wrapper carries the bench.py summary in
    ``parsed``. Throughput series key = the full metric bracket (per-
    config, like-for-like); byte/comms stamps are config-independent
    projections and key flat. Returns points contributed."""
    parsed = data.get("parsed") or {}
    return _bench_summary_points(
        points, _round_of(path), os.path.basename(path), parsed
    )


def _bench_summary_points(points: dict, rnd, source: str, parsed: dict) -> int:
    before = sum(len(v) for v in points.values())
    metric = str(parsed.get("metric", ""))
    bracket = metric[metric.find("["):] if "[" in metric else "[unkeyed]"
    _point(points, f"bench.eps_per_s{bracket}", rnd, source,
           parsed.get("value"))
    _point(points, f"bench.mfu{bracket}", rnd, source, parsed.get("mfu"))
    mb = _B_RE.search(bracket)
    if mb and isinstance(parsed.get("value"), (int, float)) \
            and parsed["value"] > 0:
        # Derived step time at this config's episode batch: B / eps * 1e3.
        _point(points, f"bench.step_ms{bracket}", rnd, source,
               round(int(mb.group(1)) / parsed["value"] * 1e3, 4))
    for key in ("step_bytes", "step_bytes_windowed", "lstm_residual_bytes",
                "comms_bytes_per_step", "comms_wire_bytes_per_step",
                "comms_overlap_frac", "comms_unoverlapped_frac"):
        _point(points, f"bench.{key}", rnd, source, parsed.get(key))
    # Round 10: per-bucket all-reduce payload (grouped from the ledger's
    # attributed flagship rows — see bench.py::_comms_overlap_stamp).
    for bucket, nbytes in sorted(
            (parsed.get("comms_bucket_bytes") or {}).items()):
        _point(points, f"bench.comms_bucket_bytes.{bucket}", rnd, source,
               nbytes)
    serving = parsed.get("serving") or {}
    _point(points, "bench.serving_continuous_over_microbatch", rnd, source,
           serving.get("continuous_over_microbatch"))
    scen = parsed.get("scenarios") or {}
    for key in ("in_domain_accuracy", "da_mixture_accuracy", "nota_best_f1"):
        _point(points, f"bench.{key}", rnd, source, scen.get(key))
    return sum(len(v) for v in points.values()) - before


def _roofline_points(points: dict, path: str, data: dict) -> int:
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    for key in ("step_bytes", "step_bytes_no_remat", "step_bytes_full_cs",
                "lstm_residual_bytes", "floor_ms_nominal_v5e"):
        _point(points, f"roofline.{key}", rnd, src, data.get(key))
    return sum(len(v) for v in points.values()) - before


def _comms_points(points: dict, path: str, data: dict) -> int:
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    flag = data.get("dp8_tokencache_lazy_flagship") or {}
    _point(points, "comms.flagship_payload_bytes", rnd, src,
           flag.get("total_bytes_per_step_per_device"))
    _point(points, "comms.flagship_unattributed_bytes", rnd, src,
           flag.get("unattributed_bytes"))
    lazy = data.get("dp8_tokencache_lazy") or {}
    _point(points, "comms.dp8_lazy_payload_bytes", rnd, src,
           lazy.get("total_bytes_per_step_per_device"))
    # Round 10+: measured whole-step overlap on the flagship leg (the
    # ledger's per-collective dataflow windows priced at the v5e HBM:ICI
    # ratio, wire-weighted) plus the bucketed lazy leg's payload — the
    # bucketed restructure's byte win gets its own diet band.
    ov = flag.get("overlap") or {}
    _point(points, "comms.flagship_overlap_frac", rnd, src,
           ov.get("overlap_frac"))
    _point(points, "comms.flagship_wire_bytes", rnd, src,
           ov.get("total_wire_bytes"))
    bucketed = data.get("dp8_lazy_bucketed") or {}
    _point(points, "comms.dp8_lazy_bucketed_payload_bytes", rnd, src,
           bucketed.get("total_bytes_per_step_per_device"))
    return sum(len(v) for v in points.values()) - before


def _serve_points(points: dict, path: str, data: dict) -> int:
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    comp = data.get("comparison") or {}
    _point(points, "serve.closed_qps_ratio", rnd, src,
           comp.get("closed_qps_ratio"))
    for arm in ("continuous", "microbatch"):
        a = (data.get("arms") or {}).get(arm) or {}
        _point(points, f"serve.closed_qps.{arm}", rnd, src,
               (a.get("closed") or {}).get("qps"))
        _point(points, f"serve.open_p99_ms.{arm}", rnd, src,
               (a.get("open") or {}).get("p99_ms"))
        drill = a.get("swap_drill") or {}
        for k in ("dropped", "rejected"):
            _point(points, f"serve.drill_{k}.{arm}", rnd, src,
                   drill.get(k))
    return sum(len(v) for v in points.values()) - before


def _chaos_points(points: dict, path: str, data: dict) -> int:
    """CHAOS_r*.json (tools/loadgen.py --chaos_drill): the containment
    zero-bands plus the drill's pass/recovery record."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    _point(points, "chaos.dropped_during_rollback", rnd, src,
           zero.get("dropped_during_rollback"))
    _point(points, "chaos.steady_recompiles", rnd, src,
           zero.get("steady_recompiles"))
    _point(points, "chaos.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    drill = data.get("chaos_drill") or {}
    ckpt = drill.get("ckpt") or {}
    _point(points, "chaos.ckpt_bitwise_recovery", rnd, src,
           1.0 if ckpt.get("bitwise_equal") else 0.0)
    _point(points, "chaos.breaker_open_criticals", rnd, src,
           drill.get("breaker_open_criticals"))
    _point(points, "chaos.injected_faults", rnd, src, drill.get("injected"))
    return sum(len(v) for v in points.values()) - before


def _fleet_points(points: dict, path: str, data: dict) -> int:
    """FLEET_r*.json (tools/loadgen.py --fleet): the router-tier soak —
    zero-bands, drill pass/recovery, placement churn, fan-out publish
    wall time, and per-replica qps (recorded, unbanded)."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    _point(points, "fleet.dropped_during_failover", rnd, src,
           zero.get("dropped_during_failover"))
    _point(points, "fleet.steady_recompiles", rnd, src,
           zero.get("steady_recompiles"))
    _point(points, "fleet.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    placement = data.get("placement") or {}
    _point(points, "fleet.tenants", rnd, src, placement.get("tenants"))
    _point(points, "fleet.add_churn_frac", rnd, src,
           placement.get("add_churn_frac"))
    fanout = data.get("fanout_publish") or {}
    _point(points, "fleet.fanout_publish_s", rnd, src,
           fanout.get("publish_s"))
    kill = data.get("replica_kill") or {}
    _point(points, "fleet.kill_recovered", rnd, src,
           1.0 if kill.get("recovered") else 0.0)
    traffic = data.get("traffic") or {}
    _point(points, "fleet.qps", rnd, src, traffic.get("qps"))
    _point(points, "fleet.p99_ms", rnd, src, traffic.get("p99_ms"))
    for rid, row in sorted((data.get("per_replica") or {}).items()):
        if isinstance(row, dict):
            _point(points, f"fleet.replica_qps.{rid}", rnd, src,
                   row.get("qps"))
    return sum(len(v) for v in points.values()) - before


def _adapt_points(points: dict, path: str, data: dict) -> int:
    """ADAPT_r*.json (tools/loadgen.py --adapt_drill): the self-healing
    loop's zero-bands (nothing dropped or recompiled by the adaptation
    publish; the forced-canary-failure arm publishes nothing), the
    recovery/exhaustion floors, and the recorded (unbanded) wall
    times."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    for key in ("dropped_during_publish", "steady_recompiles",
                "unexpected_publishes"):
        _point(points, f"adapt.{key}", rnd, src, zero.get(key))
    _point(points, "adapt.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    success = data.get("success") or {}
    _point(points, "adapt.recovered", rnd, src,
           1.0 if success.get("verified") else 0.0)
    _point(points, "adapt.recover_s", rnd, src, success.get("recover_s"))
    _point(points, "adapt.finetune_s", rnd, src, success.get("finetune_s"))
    _point(points, "adapt.publish_s", rnd, src, success.get("publish_s"))
    failure = data.get("canary_failure") or {}
    _point(points, "adapt.exhausted_latched", rnd, src,
           1.0 if failure.get("exhausted") else 0.0)
    return sum(len(v) for v in points.values()) - before


def _recovery_points(points: dict, path: str, data: dict) -> int:
    """RECOVERY_r*.json (tools/loadgen.py --recovery_drill): the
    durable-control-plane drill — zero-bands (tenant loss, steady
    recompiles, drops during catch-up), the bitwise/placement/torn-tail
    floors, and recorded (unbanded) journal + restart counts."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    for key in ("tenants_lost", "steady_recompiles",
                "dropped_during_catchup"):
        _point(points, f"recovery.{key}", rnd, src, zero.get(key))
    _point(points, "recovery.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    rk = data.get("router_kill") or {}
    _point(points, "recovery.directory_bitwise", rnd, src,
           1.0 if rk.get("directory_bitwise") else 0.0)
    _point(points, "recovery.placement_identical", rnd, src,
           1.0 if rk.get("placement_identical") else 0.0)
    _point(points, "recovery.reregistered", rnd, src,
           rk.get("reregistered"))
    _point(points, "recovery.caught_up", rnd, src, rk.get("caught_up"))
    rep = data.get("replica_kill") or {}
    _point(points, "recovery.restart_attempts", rnd, src,
           rep.get("restart_attempts"))
    tt = data.get("torn_tail") or {}
    _point(points, "recovery.torn_prefix_recovered", rnd, src,
           1.0 if tt.get("prefix_recovered") else 0.0)
    _point(points, "recovery.journal_records", rnd, src,
           data.get("journal_records_at_kill"))
    return sum(len(v) for v in points.values()) - before


def _elastic_points(points: dict, path: str, data: dict) -> int:
    """ELASTIC_r*.json (tools/loadgen.py --elastic_drill): the
    elasticity drill — zero-bands (drops through scale events and the
    promotion window, tenant loss, steady recompiles), the pass /
    promotion-bitwise / split-brain floors, and recorded (unbanded)
    warm-compile, move, and tail counts."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    for key in ("dropped_during_scale", "dropped_during_promotion",
                "tenants_lost", "steady_recompiles"):
        _point(points, f"scale.{key}", rnd, src, zero.get(key))
    _point(points, "scale.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    so = data.get("scale_out") or {}
    _point(points, "scale.warm_compiles", rnd, src,
           so.get("warm_compiles"))
    _point(points, "scale.moved", rnd, src, so.get("moved"))
    di = data.get("drain_in") or {}
    _point(points, "scale.drain_inflight", rnd, src,
           di.get("inflight_at_drain"))
    pr = data.get("promotion") or {}
    _point(points, "scale.promotion_recovered", rnd, src,
           1.0 if (pr.get("directory_bitwise")
                   and pr.get("placement_identical")
                   and pr.get("tenants_lost") == 0) else 0.0)
    _point(points, "scale.split_brain_refused", rnd, src,
           1.0 if pr.get("split_brain_refused") else 0.0)
    _point(points, "scale.degraded_during_promotion", rnd, src,
           pr.get("degraded_during_promotion"))
    return sum(len(v) for v in points.values()) - before


def _obsfleet_points(points: dict, path: str, data: dict) -> int:
    """OBSFLEET_r*.json (tools/loadgen.py --fleet_obs_drill): the fleet
    observability drill — zero-bands (orphan spans, unstitched hops),
    the pass / full-coverage / incident-ordering floors, and recorded
    (unbanded) hop-tax percentiles + clock-offset spread."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    for key in ("orphan_spans", "unstitched_frac"):
        _point(points, f"obsfleet.{key}", rnd, src, zero.get(key))
    _point(points, "obsfleet.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    st = data.get("stitching") or {}
    _point(points, "obsfleet.stitch_coverage", rnd, src,
           st.get("stitch_coverage"))
    _point(points, "obsfleet.hop_records", rnd, src,
           st.get("hop_records"))
    tl = data.get("timeline") or {}
    _point(points, "obsfleet.incidents_ordered", rnd, src,
           1.0 if tl.get("incidents_ordered") else 0.0)
    _point(points, "obsfleet.timeline_events", rnd, src,
           tl.get("events"))
    hp = data.get("hop") or {}
    _point(points, "obsfleet.hop_ms_p50", rnd, src, hp.get("hop_ms_p50"))
    _point(points, "obsfleet.hop_ms_p99", rnd, src, hp.get("hop_ms_p99"))
    ck = data.get("clock") or {}
    _point(points, "obsfleet.max_offset_ms", rnd, src,
           ck.get("max_offset_ms"))
    return sum(len(v) for v in points.values()) - before


def _quant_points(points: dict, path: str, data: dict) -> int:
    """QUANT_r*.json (tools/loadgen.py --quant_ab): the quantized-
    serving A/B — zero-bands (dropped, steady recompiles across all
    three arms), the pass / verdict-agreement / bytes-ratio floors, and
    recorded (unbanded) per-arm qps/p99, margin drift, resident bytes
    per tenant and the projected tenants-per-chip density."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    for key in ("dropped", "steady_recompiles"):
        _point(points, f"quant.{key}", rnd, src, zero.get(key))
    _point(points, "quant.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    arms = data.get("arms") or {}
    for dt, arm in sorted(arms.items()):
        if dt != "f32":
            _point(points, f"quant.agreement.{dt}", rnd, src,
                   arm.get("quant_agreement"))
            _point(points, f"quant.margin_drift.{dt}", rnd, src,
                   arm.get("quant_margin_drift"))
        _point(points, f"quant.qps.{dt}", rnd, src, arm.get("qps"))
        _point(points, f"quant.p99_ms.{dt}", rnd, src, arm.get("p99_ms"))
        _point(points, f"quant.bytes_per_tenant.{dt}", rnd, src,
               arm.get("resident_bytes_per_tenant"))
    den = data.get("density") or {}
    _point(points, "quant.bytes_ratio_f32_over_int8", rnd, src,
           den.get("bytes_ratio_f32_over_int8"))
    for dt, v in sorted((den.get("tenants_per_chip_projected")
                         or {}).items()):
        _point(points, f"quant.tenants_per_chip_projected.{dt}",
               rnd, src, v)
    return sum(len(v) for v in points.values()) - before


def _geom_points(points: dict, path: str, data: dict) -> int:
    """GEOM_r*.json (tools/loadgen.py --geom_ab): the mixed-geometry
    A/B — zero-bands (tiered arm dropped / steady recompiles through a
    tier crossing and a dtype flip), the pass floor, per-arm program
    counts and qps (the compiled-program win recorded as a ratio), and
    the (N, K) scenario grid accuracies with their CIs — one banded
    floor per grid point."""
    rnd, src = _round_of(path), os.path.basename(path)
    before = sum(len(v) for v in points.values())
    zero = data.get("zero_bands") or {}
    for key in ("tiered_dropped", "tiered_steady_recompiles"):
        _point(points, f"geom.{key}", rnd, src, zero.get(key))
    _point(points, "geom.passed", rnd, src,
           1.0 if data.get("passed") else 0.0)
    arms = data.get("arms") or {}
    for label, arm in sorted(arms.items()):
        _point(points, f"geom.programs.{label}", rnd, src,
               arm.get("program_cache_keys"))
        _point(points, f"geom.qps.{label}", rnd, src, arm.get("qps"))
        _point(points, f"geom.p99_ms.{label}", rnd, src,
               arm.get("p99_ms"))
        _point(points, f"geom.steady_recompiles.{label}", rnd, src,
               arm.get("steady_recompiles"))
    t = (arms.get("tiered") or {}).get("program_cache_keys")
    e = (arms.get("exact") or {}).get("program_cache_keys")
    if t and e:
        _point(points, "geom.program_ratio_exact_over_tiered", rnd, src,
               round(e / t, 3))
    for key, leg in sorted((data.get("grid") or {}).items()):
        _point(points, f"geom.grid_acc.{key}", rnd, src,
               leg.get("accuracy"))
        _point(points, f"geom.grid_ci95.{key}", rnd, src,
               leg.get("acc_ci95"))
    return sum(len(v) for v in points.values()) - before


_EXTRACTORS = (
    ("BENCH_r*.json", _bench_points),
    ("ROOFLINE_r*.json", _roofline_points),
    ("COMMS_r*.json", _comms_points),
    ("SERVE_r*.json", _serve_points),
    ("CHAOS_r*.json", _chaos_points),
    ("FLEET_r*.json", _fleet_points),
    ("ADAPT_r*.json", _adapt_points),
    ("RECOVERY_r*.json", _recovery_points),
    ("ELASTIC_r*.json", _elastic_points),
    ("OBSFLEET_r*.json", _obsfleet_points),
    ("QUANT_r*.json", _quant_points),
    ("GEOM_r*.json", _geom_points),
)


def build_trend(root: Path) -> tuple[dict, list[str]]:
    """(trend dict, problems). A committed artifact contributing zero
    points is a problem — the trajectory must never silently lose an
    input. Output is DETERMINISTIC in the inputs (no timestamps), so
    --check can demand committed-TREND == regenerated-TREND byte
    equality."""
    points: dict[str, list[dict]] = {}
    inputs: list[str] = []
    problems: list[str] = []
    for pattern, extract in _EXTRACTORS:
        for path in sorted(glob.glob(str(root / pattern))):
            name = os.path.basename(path)
            inputs.append(name)
            try:
                data = json.loads(Path(path).read_text())
            except (json.JSONDecodeError, OSError) as e:
                problems.append(f"{name}: unreadable ({e})")
                continue
            if not isinstance(data, dict):
                problems.append(f"{name}: not a JSON object")
                continue
            if extract(points, path, data) == 0:
                problems.append(
                    f"{name}: contributed ZERO trajectory points — "
                    f"extractor out of date with the artifact schema"
                )
    live_path = root / LIVE_NAME
    live_rows = 0
    if live_path.exists():
        for lineno, line in enumerate(live_path.read_text().splitlines(), 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                problems.append(f"{LIVE_NAME}:{lineno}: not JSON")
                continue
            if not isinstance(row, dict):
                problems.append(f"{LIVE_NAME}:{lineno}: not a JSON object")
                continue
            live_rows += 1
            _bench_summary_points(
                points, None, f"{LIVE_NAME}:{lineno}", row
            )
    series = {}
    for name in sorted(points):
        pts = points[name]
        entry: dict = {"points": pts}
        rule = _band_rule(name)
        if rule is not None:
            entry["band"] = {"rule": rule[0], "tol": rule[1]}
        series[name] = entry
    trend = {
        "series": series,
        "inputs": inputs,
        "live_rows": live_rows,
    }
    return trend, problems


def _strip_live(trend: dict) -> dict:
    """The trend with TREND_INPUT.jsonl-derived points removed — the
    ARTIFACT-ONLY view the staleness gate compares. Live rows are
    machine-local by nature (every bench run appends one): holding the
    committed TREND.json to byte-equality INCLUDING them would fail
    tier-1 on any checkout that ever ran bench.py locally, and
    committing a live-row-bearing TREND.json would fail every CLEAN
    checkout in the other direction. The BAND gate uses the same view
    (two local runs under different sandbox weather must not fail
    tier-1 on one machine); live points are still folded into the
    WRITTEN TREND.json for visibility, and gating a fresh run is the
    --candidate path."""
    series = {}
    for name, entry in trend["series"].items():
        pts = [
            p for p in entry["points"]
            if not str(p["source"]).startswith(LIVE_NAME)
        ]
        if pts:
            series[name] = {**entry, "points": pts}
    return {"series": series, "inputs": trend["inputs"]}


# --- band checking --------------------------------------------------------

def check_band(name: str, values: list[float], rule: str, tol: float,
               candidate: float | None = None) -> str | None:
    """Validate the newest value (or an explicit ``candidate``) against
    the band its predecessors establish. Returns an error string or
    None. Series with < 2 effective points (or < 1 prior for a
    candidate) bind nothing."""
    if candidate is not None:
        prior, newest = values, candidate
    else:
        prior, newest = values[:-1], values[-1] if values else None
    if newest is None:
        return None
    if rule == "zero":
        return (None if newest == 0 else
                f"{name}: {newest} must be 0 (zero-band)")
    if rule == "floor":
        return (None if newest >= tol else
                f"{name}: {newest} below floor {tol}")
    if not prior:
        return None
    if rule == "higher":
        bar = max(prior) * (1.0 - tol)
        if newest < bar:
            return (f"{name}: {newest} out of band — below "
                    f"{bar:.4g} ((1-{tol}) x best {max(prior):.4g})")
        return None
    if rule == "lower":
        bar = min(prior) * (1.0 + tol)
        if newest > bar:
            return (f"{name}: {newest} out of band — above "
                    f"{bar:.4g} ((1+{tol}) x best {min(prior):.4g})")
        return None
    return f"{name}: unknown band rule {rule!r}"


def run_check(
    root: Path, candidate_path: str | None = None
) -> tuple[list[str], dict]:
    """(--check failures as strings (empty = green), the built trend —
    returned so main() can print counts without rebuilding)."""
    trend, problems = build_trend(root)
    errors = list(problems)
    if not trend["series"]:
        errors.append("trajectory is EMPTY: no artifacts matched")
    committed = root / TREND_NAME
    if not committed.exists():
        errors.append(f"{TREND_NAME} not committed — run bench_trend.py")
    else:
        try:
            on_disk = json.loads(committed.read_text())
        except json.JSONDecodeError as e:
            on_disk = None
            errors.append(f"{TREND_NAME} unreadable: {e.msg}")
        try:
            stale = on_disk is not None and (
                _strip_live(on_disk) != _strip_live(trend)
            )
        except (KeyError, TypeError, AttributeError):
            stale = True    # hand-edited/malformed committed trend
        if stale:
            # Artifact-only comparison: uncommitted local bench runs
            # (live rows in TREND_INPUT.jsonl) must not fail the gate —
            # see _strip_live. New/changed *_r*.json artifacts DO.
            errors.append(
                f"{TREND_NAME} is STALE: regeneration differs (new or "
                f"changed artifacts) — re-run tools/bench_trend.py and "
                f"commit the result"
            )
    # Bands gate over COMMITTED artifacts only: live TREND_INPUT.jsonl
    # rows are per-run and machine-local — two local bench runs under
    # different sandbox weather must not fail tier-1 on that machine
    # while CI stays green. Gating a fresh run is the --candidate path.
    for name, entry in _strip_live(trend)["series"].items():
        band = entry.get("band")
        if band is None:
            continue
        values = [p["value"] for p in entry["points"]]
        err = check_band(name, values, band["rule"], band["tol"])
        if err:
            errors.append(err)
    if candidate_path is not None:
        errors.extend(_check_candidate(trend, candidate_path))
    return errors, trend


def _check_candidate(trend: dict, candidate_path: str) -> list[str]:
    """Validate a fresh bench summary (bench.py's stdout JSON object, or
    a driver wrapper carrying it in ``parsed``) against committed bands."""
    try:
        data = json.loads(Path(candidate_path).read_text())
    except (json.JSONDecodeError, OSError) as e:
        return [f"candidate {candidate_path}: unreadable ({e})"]
    if not isinstance(data, dict):
        return [f"candidate {candidate_path}: not a JSON object"]
    parsed = data.get("parsed", data)
    if not isinstance(parsed, dict):
        return [f"candidate {candidate_path}: 'parsed' is not an object"]
    cand_points: dict[str, list[dict]] = {}
    n = _bench_summary_points(cand_points, None, candidate_path, parsed)
    if n == 0:
        return [f"candidate {candidate_path}: no recognizable bench fields"]
    errors = []
    # Bands from COMMITTED artifacts only (same _strip_live view as the
    # tier-1 gate): a lucky machine-local live row must not ratchet the
    # bar a later run on the same machine is judged against.
    artifact_series = _strip_live(trend)["series"]
    for name, pts in cand_points.items():
        rule = _band_rule(name)
        if rule is None:
            continue
        committed = artifact_series.get(name)
        prior = [p["value"] for p in committed["points"]] if committed else []
        for p in pts:
            err = check_band(name, prior, rule[0], rule[1],
                             candidate=p["value"])
            if err:
                errors.append(f"candidate: {err}")
    return errors


# --- cli ------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fold committed perf artifacts into TREND.json and "
                    "gate fresh legs against per-metric bands"
    )
    ap.add_argument("--root", default=str(_REPO),
                    help="repo root holding the *_r*.json artifacts")
    ap.add_argument("--check", action="store_true",
                    help="validate only (coverage + staleness + bands); "
                         "exit 1 on any failure; writes nothing")
    ap.add_argument("--candidate",
                    help="a fresh bench summary JSON to validate against "
                         "the committed bands (with --check)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the trend as JSON to stdout")
    args = ap.parse_args(argv)
    root = Path(args.root)

    if args.check or args.candidate:
        errors, trend = run_check(root, args.candidate)
        for e in errors:
            print(f"trend check: {e}", file=sys.stderr)
        n_pts = sum(
            len(s["points"]) for s in trend["series"].values()
        )
        print(f"{'FAIL' if errors else 'OK'}: {len(trend['series'])} "
              f"series, {n_pts} points, {len(errors)} failures")
        return 1 if errors else 0

    trend, problems = build_trend(root)
    for p in problems:
        print(f"trend: WARNING: {p}", file=sys.stderr)
    out = root / TREND_NAME
    out.write_text(json.dumps(trend, indent=1) + "\n")
    n_pts = sum(len(s["points"]) for s in trend["series"].values())
    print(f"wrote {out}: {len(trend['series'])} series, {n_pts} points "
          f"from {len(trend['inputs'])} artifacts + {trend['live_rows']} "
          f"live rows")
    if args.as_json:
        print(json.dumps(trend, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
