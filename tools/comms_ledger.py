#!/usr/bin/env python3
"""Per-step collective-communication ledger from compiled HLO (round-5
VERDICT item 8).

For each parallelism leg the dryrun exercises (dp, dp+tp, sp/ring, ep/MoE,
pp/GPipe, ZeRO-1, and the production token-cache fused path), jit-compile
the sharded train step on the 8-virtual-device CPU mesh
(``jit(...).lower(...).compile()``), walk the SPMD-partitioned HLO text,
and sum the output bytes of every collective op (all-reduce, all-gather,
reduce-scatter, collective-permute, all-to-all). The result is
bytes/step/device of ICI traffic as the COMPILER actually scheduled it —
arithmetic, not design claims ("scales over ICI").

Bytes are per-device per-step at the dryrun's tiny shapes; the ledger also
re-derives the dominant term analytically (gradient allreduce ~= 2x param
bytes for ring allreduce) so BASELINE.md can project to flagship shapes
and v4-8 scale. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/comms_ledger.py [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

# `f32[4,128]{1,0}` or scalar `f32[]` — shapes as HLO prints them.
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)


def _shape_bytes(shape_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_bytes(hlo_text: str) -> dict[str, dict[str, int]]:
    """HLO text -> {collective op kind: {count, bytes}} from op OUTPUT
    shapes (ring all-reduce moves ~2x this on the wire; the ledger reports
    payload bytes and lets the projection apply the algorithm factor)."""
    out: dict[str, dict[str, int]] = {}
    for line in hlo_text.splitlines():
        line = line.strip()
        # Skip fusion/computation headers; match `<shape> <op>(`  e.g.
        # `%ar = f32[128]{0} all-reduce(...)`. Async pairs: the base op is
        # captured LAZILY so `-start`/`-done` land in the suffix group
        # (a greedy `[a-z\-]+` would swallow them and the op-name lookup
        # would silently drop every async collective — review finding,
        # round 5); `-done` ops are skipped, `-start` carries the shape.
        m = re.match(r"%?[\w.\-]+\s*=\s*(\([^)]*\)|[\w\[\],{}: ]+?)\s+"
                     r"([a-z\-]+?)(-start|-done)?\(", line)
        if not m:
            continue
        shape_str, op, suffix = m.groups()
        if op not in _COLLECTIVES or suffix == "-done":
            continue
        entry = out.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += _shape_bytes(shape_str)
    return out


def _tiny(**kw):
    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    base = dict(
        encoder="bilstm", train_n=3, n=3, k=2, q=2, batch_size=8,
        max_length=16, vocab_size=302, compute_dtype="float32",
        lstm_hidden=32, att_dim=16, induction_dim=32, ntn_slices=16,
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _legs():
    """[(name, cfg, make mesh, build step+args)] — mirrors the dryrun legs."""
    import jax

    import __graft_entry__ as ge
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    def plain(cfg, mesh):
        model, params, sup, qry, label = ge._build(cfg)
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    legs = []

    cfg = _tiny(dp=8)
    legs.append(("dp8", cfg, make_mesh(dp=8), plain))

    cfg = _tiny(dp=4, tp=2)
    legs.append(("dp4_tp2", cfg, make_mesh(dp=4, tp=2), plain))

    cfg = _tiny(dp=8, zero_opt=True)
    legs.append(("dp8_zero1", cfg, make_mesh(dp=8), plain))

    def sp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.ring import (
            make_ring_attention,
        )

        model, params, sup, qry, label = ge._build(
            cfg, attn_impl=make_ring_attention(mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, dp=2, sp=4,
                batch_size=2)
    legs.append(("dp2_sp4_ring", cfg, make_mesh(dp=2, sp=4), sp_leg))

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, moe_experts=4,
                moe_top_k=2, moe_every=2, dp=2, ep=4, batch_size=2)
    legs.append(("dp2_ep4_moe", cfg, make_mesh(dp=2, ep=4), plain))

    def pp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.pipeline import (
            make_gpipe,
        )

        gp = make_gpipe(mesh, microbatches=cfg.pp_microbatches,
                        batch_axis="dp" if mesh.shape["dp"] > 1 else None)
        model, params, sup, qry, label = ge._build(cfg, pipeline_impl=gp)
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=4,
                tfm_model=32, tfm_heads=2, tfm_ff=64, tfm_stacked=True,
                dp=2, pp=4, pp_microbatches=2, batch_size=4)
    legs.append(("dp2_pp4_gpipe", cfg, make_mesh(dp=2, pp=4), pp_leg))

    def cached_leg(cfg, mesh):
        from jax.sharding import NamedSharding, PartitionSpec

        from induction_network_on_fewrel_tpu.data import (
            GloveTokenizer,
            make_synthetic_fewrel,
            make_synthetic_glove,
        )
        from induction_network_on_fewrel_tpu.models import build_model
        from induction_network_on_fewrel_tpu.native.sampler import (
            make_index_sampler,
        )
        from induction_network_on_fewrel_tpu.train.lazy_embed import (
            augment_token_table,
        )
        from induction_network_on_fewrel_tpu.train.token_cache import (
            make_token_cached_multi_train_step,
            tokenize_dataset,
        )

        vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
        ds = make_synthetic_fewrel(
            num_relations=6, instances_per_relation=cfg.k + cfg.q + 2,
            vocab_size=cfg.vocab_size - 2,
        )
        tok = GloveTokenizer(vocab, max_length=cfg.max_length)
        table_np, sizes = tokenize_dataset(ds, tok)
        if cfg.embed_optimizer == "lazy":
            table_np, uids = augment_token_table(table_np)
            table_np = {**table_np, "uids": uids}
        table = {
            k: jax.device_put(v, NamedSharding(mesh, PartitionSpec()))
            for k, v in table_np.items()
        }
        idx = make_index_sampler(
            sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0,
            backend="python",
        )
        model = build_model(cfg, glove_init=vocab.vectors)
        si, qi, lab = idx.sample_fused(cfg.steps_per_call)
        sup = {k: v[si[0]] for k, v in table_np.items() if k != "uids"}
        qry = {k: v[qi[0]] for k, v in table_np.items() if k != "uids"}
        state = init_state(model, cfg, sup, qry)
        step = make_token_cached_multi_train_step(model, cfg, mesh, state)
        return step, (state, table, si, qi, lab)

    # steps_per_call=1 deliberately: a fused scan's in-loop collectives
    # print ONCE in static HLO but execute per iteration — dividing a
    # static count by S would undercount (review finding, round 5). The
    # S=1 compile gives the exact per-step bytes of the same body.
    cfg = _tiny(dp=8, token_cache=True, steps_per_call=1,
                embed_optimizer="lazy")
    legs.append(("dp8_tokencache_lazy", cfg, make_mesh(dp=8), cached_leg))

    return legs


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    import jax

    if "xla_force_host_platform_device_count" in os.environ["XLA_FLAGS"]:
        jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= 8, "need 8 virtual devices"

    def param_count(params) -> int:
        return sum(x.size for x in jax.tree.leaves(params))

    results = {}
    for name, cfg, mesh, build in _legs():
        step, fn_args = build(cfg, mesh)
        lowered = step.lower(*fn_args)
        compiled = lowered.compile()
        per_op = collective_bytes(compiled.as_text())
        total = sum(v["bytes"] for v in per_op.values())
        n_params = None
        try:
            n_params = param_count(fn_args[0].params)
        except Exception:
            pass
        results[name] = {
            "mesh": dict(mesh.shape),
            "collectives": per_op,
            "total_bytes_per_step_per_device": total,
            "param_count": n_params,
            "param_bytes_f32": (4 * n_params) if n_params else None,
        }
        print(f"{name}: {total} B/step/device, "
              f"{ {k: v['count'] for k, v in per_op.items()} }")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
