#!/usr/bin/env python3
"""Per-step collective-communication ledger from compiled HLO (round-5
VERDICT item 8).

For each parallelism leg the dryrun exercises (dp, dp+tp, sp/ring, ep/MoE,
pp/GPipe, ZeRO-1, and the production token-cache fused path), jit-compile
the sharded train step on the 8-virtual-device CPU mesh
(``jit(...).lower(...).compile()``), walk the SPMD-partitioned HLO text,
and sum the output bytes of every collective op (all-reduce, all-gather,
reduce-scatter, collective-permute, all-to-all). The result is
bytes/step/device of ICI traffic as the COMPILER actually scheduled it —
arithmetic, not design claims ("scales over ICI").

Bytes are per-device per-step at the dryrun's tiny shapes; the ledger also
re-derives the dominant term analytically (gradient allreduce ~= 2x param
bytes for ring allreduce) so BASELINE.md can project to flagship shapes
and v4-8 scale. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/comms_ledger.py [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

# `f32[4,128]{1,0}` or scalar `f32[]` — shapes as HLO prints them.
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)


def _shape_bytes(shape_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_bytes(hlo_text: str) -> dict[str, dict[str, int]]:
    """HLO text -> {collective op kind: {count, bytes}} from op OUTPUT
    shapes (ring all-reduce moves ~2x this on the wire; the ledger reports
    payload bytes and lets the projection apply the algorithm factor)."""
    out: dict[str, dict[str, int]] = {}
    for line in hlo_text.splitlines():
        line = line.strip()
        # Skip fusion/computation headers; match `<shape> <op>(`  e.g.
        # `%ar = f32[128]{0} all-reduce(...)`. Async pairs: the base op is
        # captured LAZILY so `-start`/`-done` land in the suffix group
        # (a greedy `[a-z\-]+` would swallow them and the op-name lookup
        # would silently drop every async collective — review finding,
        # round 5); `-done` ops are skipped, `-start` carries the shape.
        m = re.match(r"%?[\w.\-]+\s*=\s*(\([^)]*\)|[\w\[\],{}: ]+?)\s+"
                     r"([a-z\-]+?)(-start|-done)?\(", line)
        if not m:
            continue
        shape_str, op, suffix = m.groups()
        if op not in _COLLECTIVES or suffix == "-done":
            continue
        entry = out.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += _shape_bytes(shape_str)
    return out


def _tiny(**kw):
    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    base = dict(
        encoder="bilstm", train_n=3, n=3, k=2, q=2, batch_size=8,
        max_length=16, vocab_size=302, compute_dtype="float32",
        lstm_hidden=32, att_dim=16, induction_dim=32, ntn_slices=16,
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _legs():
    """[(name, cfg, make mesh, build step+args)] — mirrors the dryrun legs."""
    import jax

    import __graft_entry__ as ge
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        make_sharded_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    def plain(cfg, mesh):
        model, params, sup, qry, label = ge._build(cfg)
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    legs = []

    cfg = _tiny(dp=8)
    legs.append(("dp8", cfg, make_mesh(dp=8), plain))

    cfg = _tiny(dp=4, tp=2)
    legs.append(("dp4_tp2", cfg, make_mesh(dp=4, tp=2), plain))

    cfg = _tiny(dp=8, zero_opt=True)
    legs.append(("dp8_zero1", cfg, make_mesh(dp=8), plain))

    def sp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.ring import (
            make_ring_attention,
        )

        model, params, sup, qry, label = ge._build(
            cfg, attn_impl=make_ring_attention(mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, dp=2, sp=4,
                batch_size=2)
    legs.append(("dp2_sp4_ring", cfg, make_mesh(dp=2, sp=4), sp_leg))

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, moe_experts=4,
                moe_top_k=2, moe_every=2, dp=2, ep=4, batch_size=2)
    legs.append(("dp2_ep4_moe", cfg, make_mesh(dp=2, ep=4), plain))

    def pp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.pipeline import (
            make_gpipe,
        )

        gp = make_gpipe(mesh, microbatches=cfg.pp_microbatches,
                        batch_axis="dp" if mesh.shape["dp"] > 1 else None)
        model, params, sup, qry, label = ge._build(cfg, pipeline_impl=gp)
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=4,
                tfm_model=32, tfm_heads=2, tfm_ff=64, tfm_stacked=True,
                dp=2, pp=4, pp_microbatches=2, batch_size=4)
    legs.append(("dp2_pp4_gpipe", cfg, make_mesh(dp=2, pp=4), pp_leg))

    # steps_per_call=1 deliberately: a fused scan's in-loop collectives
    # print ONCE in static HLO but execute per iteration — dividing a
    # static count by S would undercount (review finding, round 5). The
    # S=1 compile gives the exact per-step bytes of the same body.
    cfg = _tiny(dp=8, token_cache=True, steps_per_call=1,
                embed_optimizer="lazy")
    legs.append(("dp8_tokencache_lazy", cfg, make_mesh(dp=8), _cached_leg))

    return legs


def _cached_leg(cfg, mesh):
    """Build the token-cache lazy fused step (any shape: the tiny dryrun
    leg AND the flagship leg share this builder; the corpus stays small —
    the table's 400k rows, not the sentences, are what scale)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        augment_token_table,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_train_step,
        tokenize_dataset,
    )

    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=max(6, cfg.n + 1),
        instances_per_relation=cfg.k + cfg.q + 2,
        vocab_size=min(cfg.vocab_size - 2, 2000),
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    if cfg.embed_optimizer == "lazy":
        table_np, uids = augment_token_table(table_np)
        table_np = {**table_np, "uids": uids}
    table = {
        k: jax.device_put(v, NamedSharding(mesh, PartitionSpec()))
        for k, v in table_np.items()
    }
    idx = make_index_sampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0,
        backend="python",
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    si, qi, lab = idx.sample_fused(cfg.steps_per_call)
    sup = {k: v[si[0]] for k, v in table_np.items() if k != "uids"}
    qry = {k: v[qi[0]] for k, v in table_np.items() if k != "uids"}
    state = init_state(model, cfg, sup, qry)
    step = make_token_cached_multi_train_step(model, cfg, mesh, state)
    return step, (state, table, si, qi, lab)


# Round-5's projection (BASELINE.md comms section) modeled ONLY the dp
# gradient all-reduce: non-embedding grads ~5.05 MB f32 + compact
# lazy-row cotangent ~0.4 MB => 5.45 MB payload, 10.7 MB ring wire. The
# round-6 flagship compile REFUTED it: the partitioned HLO additionally
# all-gathers the full [L, M, word_dim] f32 embedding across dp
# (25.6 MB/step/device at the flagship shape — present in the round-5
# tiny-shape leg all along as its unattributed 306 KiB all-gather, just
# never scaled up) plus ~2 MB of resharding permutes. The projection
# below is the CORRECTED model; check_flagship asserts the compiled
# payload stays within 40% of it, which still catches the failure mode
# the check exists for (an accidentally dense table all-reduce would be
# ~80 MB, 2.4x the band). Chip follow-up recorded in BASELINE.md: the
# all-gather looks avoidable (local demb scatter-add + [U, D] row
# all-reduce), worth a sharding-hint A/B on silicon.
FLAGSHIP_GRAD_PAYLOAD = 5.45e6


def flagship_payload_projection(cfg) -> float:
    """Corrected payload model: grad all-reduce + the [L, M, word_dim]
    f32 embedding all-gather + ~2 MB resharding slack."""
    m_rows = cfg.batch_size * (cfg.n * cfg.k + cfg.n * cfg.q)
    emb_ag = cfg.max_length * m_rows * cfg.word_dim * 4
    return FLAGSHIP_GRAD_PAYLOAD + emb_ag + 2e6


def flagship_leg():
    """(name, cfg, mesh, build) for the REAL-shape production path:
    vocab 400,002, B=64, L=40, token-cache lazy, dp=8."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.parallel import make_mesh

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=64, max_length=40,
        vocab_size=400002, compute_dtype="bfloat16", dp=8,
        token_cache=True, steps_per_call=1, embed_optimizer="lazy",
    )
    return ("dp8_tokencache_lazy_flagship", cfg, make_mesh(dp=8), _cached_leg)


def check_flagship(cfg, result: dict, tol: float = 0.4) -> None:
    """Assert the compiled flagship payload is within ``tol`` (fractional)
    of the corrected projection. A band, not an equality: the model
    carries the two structural terms (gradient all-reduce + embedding
    all-gather) and slack for metric/clip reductions and partitioner
    resharding — the assertion catches a shape-dependent GSPMD blowup or
    a silent regression of the comms story, not formula rounding."""
    total = result["total_bytes_per_step_per_device"]
    proj = flagship_payload_projection(cfg)
    lo, hi = proj * (1 - tol), proj * (1 + tol)
    assert lo <= total <= hi, (
        f"flagship collective payload {total / 1e6:.2f} MB/step/device "
        f"outside [{lo / 1e6:.2f}, {hi / 1e6:.2f}] — the corrected "
        f"round-6 projection ({proj / 1e6:.2f} MB payload: grads "
        f"{FLAGSHIP_GRAD_PAYLOAD / 1e6:.2f} + [L,M,word_dim] f32 "
        "embedding all-gather + resharding) no longer describes what "
        "GSPMD schedules at the real shape"
    )
    # Wire estimate at d=8: ring AR moves 2(d-1)/d of its payload, ring
    # AG (d-1)/d of the gathered size; permutes ~1x.
    ar = sum(
        v["bytes"] for k, v in result["collectives"].items()
        if k in ("all-reduce", "reduce-scatter")
    )
    ag = result["collectives"].get("all-gather", {}).get("bytes", 0)
    rest = total - ar - ag
    wire = 2 * 7 / 8 * ar + 7 / 8 * ag + rest
    print(
        f"flagship: payload {total / 1e6:.2f} MB/step/device (projection "
        f"{proj / 1e6:.2f}, within {tol:.0%}); wire ~{wire / 1e6:.1f} MB "
        f"-> ~{wire / 45e9 * 1e3:.2f} ms at v5e ICI 45 GB/s vs the "
        "~3.5 ms measured step — the round-5 '10.7 MB, ~7%' story "
        "under-counted by the embedding all-gather"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument(
        "--skip-flagship", action="store_true",
        help="skip the real-shape (vocab 400,002, B=64) flagship leg — "
             "it compiles the production fused step, which takes minutes "
             "on small hosts",
    )
    ap.add_argument(
        "--only-flagship", action="store_true",
        help="run ONLY the flagship leg + its projection assertion",
    )
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    import jax

    if "xla_force_host_platform_device_count" in os.environ["XLA_FLAGS"]:
        jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= 8, "need 8 virtual devices"

    def param_count(params) -> int:
        return sum(x.size for x in jax.tree.leaves(params))

    legs = [] if args.only_flagship else _legs()
    if not args.skip_flagship:
        legs.append(flagship_leg())

    results = {}
    for name, cfg, mesh, build in legs:
        step, fn_args = build(cfg, mesh)
        lowered = step.lower(*fn_args)
        compiled = lowered.compile()
        per_op = collective_bytes(compiled.as_text())
        total = sum(v["bytes"] for v in per_op.values())
        n_params = None
        try:
            n_params = param_count(fn_args[0].params)
        except Exception:
            pass
        results[name] = {
            "mesh": dict(mesh.shape),
            "collectives": per_op,
            "total_bytes_per_step_per_device": total,
            "param_count": n_params,
            "param_bytes_f32": (4 * n_params) if n_params else None,
        }
        print(f"{name}: {total} B/step/device, "
              f"{ {k: v['count'] for k, v in per_op.items()} }")
        if name == "dp8_tokencache_lazy_flagship":
            # VERDICT round-5 item 5: the projection must describe what
            # GSPMD actually schedules at the REAL shape, asserted here.
            check_flagship(cfg, results[name])
            results[name]["payload_projection_bytes"] = (
                flagship_payload_projection(cfg)
            )

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
