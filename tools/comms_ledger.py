#!/usr/bin/env python3
"""Per-step collective-communication ledger from compiled HLO (round-5
VERDICT item 8; round-7 attribution + compact-demb regression gate).

For each parallelism leg the dryrun exercises (dp, dp+tp, sp/ring, ep/MoE,
pp/GPipe, ZeRO-1, and the production token-cache fused path), jit-compile
the sharded train step on the 8-virtual-device CPU mesh
(``jit(...).lower(...).compile()``), walk the SPMD-partitioned HLO text,
and sum the output bytes of every collective op (all-reduce, all-gather,
reduce-scatter, collective-permute, all-to-all). The result is
bytes/step/device of ICI traffic as the COMPILER actually scheduled it —
arithmetic, not design claims ("scales over ICI").

Round-7 lesson baked in: every collective row is ATTRIBUTED to the op
that produced it, parsed from the HLO ``metadata={op_name=...}`` jax
records for every traced op (``jax.named_scope``/module paths — the same
vocabulary the obs spans bridge into XPlane profiles). The round-5 miss
this answers: the 26.1 MB/step/device flagship ``[L, M, word_dim]``
embedding all-gather sat in the tiny-shape leg for two rounds as an
anonymous 306 KiB row nobody could name, so nobody scaled it. Collectives
with NO attribution are now a loud warning and a nonzero exit under
``--strict`` — a payload term can never go uncounted again.

The flagship leg additionally enforces the compact-demb regression gate:
no single collective may move >= L*M*word_dim*4 bytes (the dense
embedding all-gather's size) — the sharding-safe demb path
(parallel/sharding.make_compact_demb_lookup) all-reduces only the compact
[U, D] touched-row gradient. tests/test_comms.py runs the same gate at
tiny shapes in tier-1.

Bytes are per-device per-step at the dryrun's tiny shapes; the ledger also
re-derives the dominant term analytically (gradient allreduce ~= 2x param
bytes for ring allreduce) so BASELINE.md can project to flagship shapes
and v4-8 scale. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/comms_ledger.py [--json out.json] [--strict]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

# `f32[4,128]{1,0}` or scalar `f32[]` — shapes as HLO prints them.
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)


def _shape_bytes(shape_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')

# op_name path components that are trace scaffolding, not provenance.
_SCAFFOLD = frozenset({"while", "body", "cond", "checkpoint", "remat"})


def _attr_label(op_name: str) -> str:
    """jax HLO op_name -> compact source label: direction (fwd/bwd) +
    the meaningful tail of the module/named_scope path.

    ``jit(multi_step)/jit(main)/while/body/transpose(jvp(InductionNetwork))
    /encoder/.../embedding/reshape`` -> ``bwd:.../embedding/reshape``.
    Explicit ``jax.named_scope`` names (e.g. the compact-demb psum's
    ``demb/compact_allreduce``) ride the same path and survive into the
    label — the bridge between obs span vocabulary and HLO metadata."""
    parts = [p for p in op_name.split("/") if p and not p.startswith("jit(")]
    bwd = any(p.startswith("transpose(") for p in parts)
    core = [
        p for p in parts
        if p not in _SCAFFOLD
        and not p.startswith("transpose(")
        and not p.startswith("jvp(")
    ]
    tail = "/".join(core[-3:]) if core else op_name
    return f"{'bwd' if bwd else 'fwd'}:{tail}"


# --- dataflow provenance (round 9) ------------------------------------------
#
# The GSPMD partitioner inserts resharding collectives (moment re-gathers,
# tp/ep/sp layout hops) with NO op_name metadata — they are compiler
# artifacts, not traced ops, so there is nothing to jax.named_scope. Those
# were the four residual attribution-debt legs (zero1 49 KB, dp4_tp2
# 12.7 KB, sp 6.1 KB, ep 1.6 KB — RUNBOOK §12, ROADMAP item 5). But a
# reshard is not anonymous in the DATAFLOW sense: it moves the value some
# attributed op produced. ``collective_rows`` therefore resolves a
# metadata-less collective by walking its operand chain to the nearest
# instruction that DOES carry op_name and labels it
# ``reshard:<that label>`` (marked ``derived``). Only a collective whose
# entire ancestor chain is metadata-free stays ``source=None`` — still a
# loud warning and a --strict failure, so the gate keeps meaning
# "every payload term is nameable", now with zero standing exceptions.

_PROVENANCE_DEPTH = 16


def _instruction_index(hlo_text: str) -> dict[str, tuple[str | None, list[str]]]:
    """Every instruction in every computation: name -> (op_name metadata or
    None, operand instruction names). Instruction names are unique
    module-wide in compiled-HLO printouts, so one flat index serves the
    provenance walk."""
    idx: dict[str, tuple[str | None, list[str]]] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rest = m.groups()
        nm = _OP_NAME_RE.search(line)
        body = rest.split(", metadata=")[0]
        idx[name] = (
            nm.group(1) if nm and nm.group(1) else None,
            _REF_RE.findall(body),
        )
    return idx


def _provenance_label(
    name: str, idx: dict[str, tuple[str | None, list[str]]],
    depth: int = _PROVENANCE_DEPTH,
) -> str | None:
    """BFS the operand chain of instruction ``name`` for the nearest
    op_name; None when every ancestor within ``depth`` is metadata-free."""
    seen = {name}
    frontier = list(idx.get(name, (None, []))[1])
    for _ in range(depth):
        if not frontier:
            return None
        nxt: list[str] = []
        for ref in frontier:
            if ref in seen:
                continue
            seen.add(ref)
            entry = idx.get(ref)
            if entry is None:   # computation ref (calls=...) — dead end
                continue
            op_name, operands = entry
            if op_name:
                return _attr_label(op_name)
            nxt.extend(operands)
        frontier = nxt
    return None


def collective_rows(hlo_text: str) -> list[dict]:
    """HLO text -> one row per collective op: ``{op, bytes, source}`` from
    op OUTPUT shapes (ring all-reduce moves ~2x this on the wire; the
    ledger reports payload bytes and lets the projection apply the
    algorithm factor). ``source`` is the attribution label parsed from the
    op's metadata; a metadata-less collective (GSPMD-inserted reshard)
    resolves through dataflow provenance to ``reshard:<producer label>``
    with ``derived=True``; None only when no ancestor carries metadata —
    an unattributed payload term (see check_attribution)."""
    rows: list[dict] = []
    pending: list[tuple[int, str]] = []   # (row index, instruction name)
    for line in hlo_text.splitlines():
        line = line.strip()
        # Skip fusion/computation headers; match `<shape> <op>(`  e.g.
        # `%ar = f32[128]{0} all-reduce(...)`. Async pairs: the base op is
        # captured LAZILY so `-start`/`-done` land in the suffix group
        # (a greedy `[a-z\-]+` would swallow them and the op-name lookup
        # would silently drop every async collective — review finding,
        # round 5); `-done` ops are skipped, `-start` carries the shape.
        m = re.match(r"%?[\w.\-]+\s*=\s*(\([^)]*\)|[\w\[\],{}: ]+?)\s+"
                     r"([a-z\-]+?)(-start|-done)?\(", line)
        if not m:
            continue
        shape_str, op, suffix = m.groups()
        if op not in _COLLECTIVES or suffix == "-done":
            continue
        nm = _OP_NAME_RE.search(line)
        rows.append({
            "op": op,
            "bytes": _shape_bytes(shape_str),
            "source": _attr_label(nm.group(1)) if nm and nm.group(1) else None,
            # The backend compiled this collective as an async start/done
            # pair (the spelling the latency-hiding scheduler overlaps);
            # CPU emits sync ops, TPU splits eligible collectives.
            "async": suffix == "-start",
        })
        if rows[-1]["source"] is None:
            im = _INSTR_RE.match(line)
            if im:
                pending.append((len(rows) - 1, im.group(1)))
    if pending:
        idx = _instruction_index(hlo_text)
        for row_i, name in pending:
            label = _provenance_label(name, idx)
            if label is not None:
                rows[row_i]["source"] = f"reshard:{label}"
                rows[row_i]["derived"] = True
    return rows


# --- overlap budget (round 8) ----------------------------------------------
#
# The compact-demb restructure (parallel/sharding.make_compact_demb_lookup)
# moved the [U, D] all-reduce out of the shard_map body: the region now
# emits per-shard partials (start) and the reduction is a free-floating
# sum whose only consumer is the word-table update (done). Whether the
# runtime actually hides the reduction is a chip question (the async
# start/done spelling above, queued A/B in BASELINE round 8) — but the
# SCHEDULING FREEDOM the restructure buys is a dataflow property of the
# compiled module, checkable on any backend: of the instructions scheduled
# after the collective, how many do NOT transitively depend on it (the
# latency-hiding window) vs how many do (its consumer chain).

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_REF_RE = re.compile(r"%([\w.\-]+)")


def _entry_instructions(hlo_text: str) -> list[tuple[str, set, str]]:
    """The ENTRY computation's instruction list, in printed (scheduled,
    for compiled modules) order: [(name, operand_names, line)]."""
    out: list[tuple[str, set, str]] = []
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            m = _INSTR_RE.match(line)
            if m:
                name, rest = m.groups()
                # Strip metadata before collecting %refs — op_name paths
                # can contain %-free text only, but stay safe.
                body = rest.split(", metadata=")[0]
                out.append((name, set(_REF_RE.findall(body)), line))
    return out


def overlap_report(
    hlo_text: str, source_frag: str = "demb/compact_allreduce"
) -> dict | None:
    """Overlap budget of the collective attributed to ``source_frag``:
    {op, dependent_ops_after, independent_ops_after, async} — the
    instructions scheduled after it that its result does/does not feed.
    ``independent_ops_after`` is the window a latency-hiding scheduler
    can fill while the reduction is in flight; ``dependent_ops_after``
    should stay small (the table-update chain). None when no collective
    carries the fragment."""
    instrs = _entry_instructions(hlo_text)
    idx = None
    for i, (name, _, line) in enumerate(instrs):
        if source_frag not in line:
            continue
        m = re.search(r"=\s*(?:\([^)]*\)|[\w\[\],{}: ]+?)\s+([a-z\-]+?)(-start)?\(", line)
        if m and m.group(1) in _COLLECTIVES:
            idx = i
            break
    if idx is None:
        return None
    name, _, line = instrs[idx]
    dependents = {name}
    dep_after = indep_after = 0
    for later_name, operands, _ in instrs[idx + 1:]:
        if operands & dependents:
            dependents.add(later_name)
            dep_after += 1
        else:
            indep_after += 1
    return {
        "op": name,
        "dependent_ops_after": dep_after,
        "independent_ops_after": indep_after,
        "async": "-start(" in line,
    }


def per_op_from_rows(rows: list[dict]) -> dict[str, dict[str, int]]:
    """collective_rows -> {collective op kind: {count, bytes}} — the ONE
    aggregation both collective_bytes and main() use."""
    out: dict[str, dict[str, int]] = {}
    for row in rows:
        entry = out.setdefault(row["op"], {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += row["bytes"]
    return out


def collective_bytes(hlo_text: str) -> dict[str, dict[str, int]]:
    """HLO text -> {collective op kind: {count, bytes}} (see
    collective_rows for the per-op attributed form)."""
    return per_op_from_rows(collective_rows(hlo_text))


def attributed_rows(rows: list[dict]) -> list[dict]:
    """Aggregate collective_rows by (op, source) -> [{op, source, count,
    bytes}], largest payload first. Unattributed rows aggregate under
    source=None so they stay visible, never silently merged."""
    agg: dict[tuple, dict] = {}
    for r in rows:
        key = (r["op"], r["source"])
        e = agg.setdefault(
            key, {"op": r["op"], "source": r["source"], "count": 0, "bytes": 0}
        )
        e["count"] += 1
        e["bytes"] += r["bytes"]
    return sorted(agg.values(), key=lambda e: -e["bytes"])


def check_attribution(name: str, rows: list[dict]) -> int:
    """Count unattributed collective bytes; print a LOUD warning when any
    exist (the round-5 failure mode: the 306 KiB anonymous all-gather that
    became 26 MB at the flagship shape). Returns the unattributed byte
    count — main() turns it into a nonzero exit under --strict."""
    anon = [r for r in rows if r["source"] is None]
    anon_bytes = sum(r["bytes"] for r in anon)
    if anon:
        print(
            f"WARNING [{name}]: {len(anon)} unattributed collective(s), "
            f"{anon_bytes} B/step/device with no op_name metadata — every "
            "payload term must be nameable (round-5 lesson: the anonymous "
            "306 KiB all-gather was the 26 MB flagship term). Inspect the "
            "compiled HLO; add a jax.named_scope at the producing op.",
            file=sys.stderr,
        )
    return anon_bytes


def _tiny(**kw):
    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    base = dict(
        encoder="bilstm", train_n=3, n=3, k=2, q=2, batch_size=8,
        max_length=16, vocab_size=302, compute_dtype="float32",
        lstm_hidden=32, att_dim=16, induction_dim=32, ntn_slices=16,
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _legs():
    """[(name, cfg, make mesh, build step+args)] — mirrors the dryrun legs."""
    import jax

    import __graft_entry__ as ge
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        demb_impl_for,
        make_sharded_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    def plain(cfg, mesh):
        model, params, sup, qry, label = ge._build(
            cfg, demb_impl=demb_impl_for(cfg, mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    legs = []

    cfg = _tiny(dp=8)
    legs.append(("dp8", cfg, make_mesh(dp=8), plain))

    cfg = _tiny(dp=4, tp=2)
    legs.append(("dp4_tp2", cfg, make_mesh(dp=4, tp=2), plain))

    cfg = _tiny(dp=8, zero_opt=True)
    legs.append(("dp8_zero1", cfg, make_mesh(dp=8), plain))

    def sp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.ring import (
            make_ring_attention,
        )

        model, params, sup, qry, label = ge._build(
            cfg, attn_impl=make_ring_attention(mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, dp=2, sp=4,
                batch_size=2)
    legs.append(("dp2_sp4_ring", cfg, make_mesh(dp=2, sp=4), sp_leg))

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, moe_experts=4,
                moe_top_k=2, moe_every=2, dp=2, ep=4, batch_size=2)
    legs.append(("dp2_ep4_moe", cfg, make_mesh(dp=2, ep=4), plain))

    def pp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.pipeline import (
            make_gpipe,
        )

        gp = make_gpipe(mesh, microbatches=cfg.pp_microbatches,
                        batch_axis="dp" if mesh.shape["dp"] > 1 else None)
        model, params, sup, qry, label = ge._build(
            cfg, pipeline_impl=gp, demb_impl=demb_impl_for(cfg, mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=4,
                tfm_model=32, tfm_heads=2, tfm_ff=64, tfm_stacked=True,
                dp=2, pp=4, pp_microbatches=2, batch_size=4)
    legs.append(("dp2_pp4_gpipe", cfg, make_mesh(dp=2, pp=4), pp_leg))

    # steps_per_call=1 deliberately: a fused scan's in-loop collectives
    # print ONCE in static HLO but execute per iteration — dividing a
    # static count by S would undercount (review finding, round 5). The
    # S=1 compile gives the exact per-step bytes of the same body.
    cfg = _tiny(dp=8, token_cache=True, steps_per_call=1,
                embed_optimizer="lazy")
    legs.append(("dp8_tokencache_lazy", cfg, make_mesh(dp=8), _cached_leg))

    return legs


def _cached_leg(cfg, mesh):
    """Build the token-cache lazy fused step (any shape: the tiny dryrun
    leg AND the flagship leg share this builder; the corpus stays small —
    the table's 400k rows, not the sentences, are what scale)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        augment_token_table,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_train_step,
        tokenize_dataset,
    )

    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=max(6, cfg.n + 1),
        instances_per_relation=cfg.k + cfg.q + 2,
        vocab_size=min(cfg.vocab_size - 2, 2000),
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    if cfg.embed_optimizer == "lazy":
        table_np, uids = augment_token_table(table_np)
        table_np = {**table_np, "uids": uids}
    table = {
        k: jax.device_put(v, NamedSharding(mesh, PartitionSpec()))
        for k, v in table_np.items()
    }
    idx = make_index_sampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0,
        backend="python",
    )
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        demb_impl_for,
    )

    model = build_model(
        cfg, glove_init=vocab.vectors, demb_impl=demb_impl_for(cfg, mesh)
    )
    si, qi, lab = idx.sample_fused(cfg.steps_per_call)
    sup = {k: v[si[0]] for k, v in table_np.items() if k != "uids"}
    qry = {k: v[qi[0]] for k, v in table_np.items() if k != "uids"}
    state = init_state(model, cfg, sup, qry)
    step = make_token_cached_multi_train_step(model, cfg, mesh, state)
    return step, (state, table, si, qi, lab)


# Round-5's projection (BASELINE.md comms section) modeled ONLY the dp
# gradient all-reduce: non-embedding grads ~5.05 MB f32 + compact
# lazy-row cotangent ~0.4 MB => 5.45 MB payload, 10.7 MB ring wire. The
# round-6 flagship compile REFUTED it: the partitioned HLO additionally
# all-gathered the full [L, M, word_dim] f32 embedding across dp
# (25.6 MB/step/device at the flagship shape — present in the round-5
# tiny-shape leg all along as its UNATTRIBUTED 306 KiB all-gather, just
# never scaled up) plus ~2 MB of resharding permutes. Round 7 removed
# the all-gather (parallel/sharding.make_compact_demb_lookup: the demb
# segment-sum stays local per shard; only the compact [U, D] touched-row
# gradient is all-reduced — already inside the 5.45 MB grad term), so
# the projection is back to the round-5 shape PLUS the resharding term
# the round-6 compile taught us to count. With every collective now
# attributed (collective_rows) the band tightens from ±40% to ±15%: the
# wide band existed only because a 26 MB term was anonymous. The same
# formulas live in utils/roofline.comms_components so bench.py's
# comms_bytes_per_step and this assertion can never drift apart.


def flagship_payload_projection(cfg) -> float:
    """Round-7 payload model: grad all-reduce (non-embedding grads + the
    compact [U, D] demb rows) + resharding slack. The [L, M, word_dim]
    all-gather is structurally absent — enforced by check_flagship's
    regression gate, not just this band."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        comms_payload_bytes,
    )

    return comms_payload_bytes(cfg)


def flagship_leg():
    """(name, cfg, mesh, build) for the REAL-shape production path:
    vocab 400,002, B=64, L=40, token-cache lazy, dp=8."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.parallel import make_mesh

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=64, max_length=40,
        vocab_size=400002, compute_dtype="bfloat16", dp=8,
        token_cache=True, steps_per_call=1, embed_optimizer="lazy",
    )
    return ("dp8_tokencache_lazy_flagship", cfg, make_mesh(dp=8), _cached_leg)


def dense_allgather_bytes(cfg) -> int:
    """The regression-gate threshold: the dense [L, M, word_dim] f32
    embedding all-gather's payload at cfg's shape. No single collective
    may reach it — if one does, a sharding change silently reintroduced
    the replicated embedding (the 26 MB round-6 finding). One home for
    the arithmetic: utils/roofline.dense_embedding_allgather_bytes."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        dense_embedding_allgather_bytes,
    )

    return dense_embedding_allgather_bytes(cfg)


def check_flagship(cfg, result: dict, tol: float = 0.15) -> None:
    """Assert (a) the compiled flagship payload is within ``tol`` of the
    projection and (b) NO single collective moves >= the dense embedding
    all-gather's bytes (the compact-demb regression gate). The band
    tightened from the round-6 ±40% to ±15%: it was wide only because
    the dominant term was unattributed — with per-collective attribution
    the model's terms are nameable against compiled rows one by one."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        comms_components,
    )

    total = result["total_bytes_per_step_per_device"]
    proj = flagship_payload_projection(cfg)
    terms = "; ".join(
        f"{name} {b / 1e6:.2f}" for name, b in comms_components(cfg)
    )
    lo, hi = proj * (1 - tol), proj * (1 + tol)
    assert lo <= total <= hi, (
        f"flagship collective payload {total / 1e6:.2f} MB/step/device "
        f"outside [{lo / 1e6:.2f}, {hi / 1e6:.2f}] — the round-7 "
        f"projection ({proj / 1e6:.2f} MB payload: {terms}) no longer "
        "describes what GSPMD schedules at the real shape"
    )
    gate = dense_allgather_bytes(cfg)
    worst = max(
        (r for r in result.get("attributed", [{"bytes": 0}])),
        key=lambda r: r["bytes"] // max(r.get("count", 1), 1),
        default={"bytes": 0},
    )
    biggest = max((r["bytes"] for r in result.get("rows", [])), default=0)
    assert biggest < gate, (
        f"REGRESSION: a single collective moves {biggest} B >= the dense "
        f"[L,M,word_dim] embedding all-gather ({gate} B) — a sharding "
        f"change reintroduced the replicated embedding (worst row: "
        f"{worst}). See parallel/sharding.make_compact_demb_lookup."
    )
    # Wire estimate from the shared ring-factor model (ONE home:
    # utils/roofline.wire_bytes), at the leg's actual dp.
    from induction_network_on_fewrel_tpu.utils.roofline import wire_bytes

    ar = sum(
        v["bytes"] for k, v in result["collectives"].items()
        if k in ("all-reduce", "reduce-scatter")
    )
    ag = result["collectives"].get("all-gather", {}).get("bytes", 0)
    wire = wire_bytes(
        {"all-reduce": ar, "all-gather": ag, "other": total - ar - ag},
        result["mesh"].get("dp", 8),
    )
    print(
        f"flagship: payload {total / 1e6:.2f} MB/step/device (projection "
        f"{proj / 1e6:.2f}, within {tol:.0%}); wire ~{wire / 1e6:.1f} MB "
        f"-> ~{wire / 45e9 * 1e3:.2f} ms at v5e ICI 45 GB/s vs the "
        "~3.5 ms measured step — was 33.7 MB payload / ~22% un-overlapped "
        "before the compact-demb path (COMMS_r06)"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument(
        "--skip-flagship", action="store_true",
        help="skip the real-shape (vocab 400,002, B=64) flagship leg — "
             "it compiles the production fused step, which takes minutes "
             "on small hosts",
    )
    ap.add_argument(
        "--only-flagship", action="store_true",
        help="run ONLY the flagship leg + its projection assertion",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit nonzero if ANY collective lacks op_name attribution — "
             "an anonymous payload term is how the 26 MB flagship "
             "all-gather hid for two rounds",
    )
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    import jax

    if "xla_force_host_platform_device_count" in os.environ["XLA_FLAGS"]:
        jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= 8, "need 8 virtual devices"

    def param_count(params) -> int:
        return sum(x.size for x in jax.tree.leaves(params))

    legs = [] if args.only_flagship else _legs()
    if not args.skip_flagship:
        legs.append(flagship_leg())

    results = {}
    anon_total = 0
    for name, cfg, mesh, build in legs:
        step, fn_args = build(cfg, mesh)
        lowered = step.lower(*fn_args)
        compiled = lowered.compile()
        hlo_text = compiled.as_text()
        rows = collective_rows(hlo_text)
        attributed = attributed_rows(rows)
        anon_total += check_attribution(name, rows)
        per_op = per_op_from_rows(rows)
        total = sum(v["bytes"] for v in per_op.values())
        n_params = None
        try:
            n_params = param_count(fn_args[0].params)
        except Exception:
            pass
        results[name] = {
            "mesh": dict(mesh.shape),
            "collectives": per_op,
            "attributed": attributed,
            "unattributed_bytes": sum(
                r["bytes"] for r in rows if r["source"] is None
            ),
            "async_collectives": sum(1 for r in rows if r.get("async")),
            "total_bytes_per_step_per_device": total,
            "param_count": n_params,
            "param_bytes_f32": (4 * n_params) if n_params else None,
        }
        overlap = overlap_report(hlo_text)
        if overlap is not None:
            # Round-8 overlap restructure: the demb all-reduce floats free
            # between the per-shard partials and the table update — record
            # the dataflow window a latency-hiding scheduler has.
            results[name]["demb_overlap"] = overlap
        print(f"{name}: {total} B/step/device, "
              f"{ {k: v['count'] for k, v in per_op.items()} }")
        for row in attributed[:6]:
            print(f"  {row['bytes']:>10} B x{row['count']:<3} {row['op']:<19} "
                  f"{row['source'] or 'UNATTRIBUTED'}")
        if overlap is not None:
            print(
                f"  demb overlap window: {overlap['independent_ops_after']} "
                f"independent ops schedulable during the reduction, "
                f"{overlap['dependent_ops_after']} dependent (table-update "
                f"chain); async spelling: {overlap['async']}"
            )
        if name == "dp8_tokencache_lazy_flagship":
            # VERDICT round-5 item 5: the projection must describe what
            # GSPMD actually schedules at the REAL shape, asserted here —
            # plus the round-7 regression gate (no dense-sized collective).
            results[name]["rows"] = rows
            check_flagship(cfg, results[name])
            del results[name]["rows"]
            results[name]["payload_projection_bytes"] = (
                flagship_payload_projection(cfg)
            )
            results[name]["dense_allgather_gate_bytes"] = (
                dense_allgather_bytes(cfg)
            )

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {args.json}")
    if args.strict and anon_total:
        print(f"--strict: {anon_total} unattributed collective bytes",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
