#!/usr/bin/env python3
"""Per-step collective-communication ledger from compiled HLO (round-5
VERDICT item 8; round-7 attribution + compact-demb regression gate).

For each parallelism leg the dryrun exercises (dp, dp+tp, sp/ring, ep/MoE,
pp/GPipe, ZeRO-1, and the production token-cache fused path), jit-compile
the sharded train step on the 8-virtual-device CPU mesh
(``jit(...).lower(...).compile()``), walk the SPMD-partitioned HLO text,
and sum the output bytes of every collective op (all-reduce, all-gather,
reduce-scatter, collective-permute, all-to-all). The result is
bytes/step/device of ICI traffic as the COMPILER actually scheduled it —
arithmetic, not design claims ("scales over ICI").

Round-7 lesson baked in: every collective row is ATTRIBUTED to the op
that produced it, parsed from the HLO ``metadata={op_name=...}`` jax
records for every traced op (``jax.named_scope``/module paths — the same
vocabulary the obs spans bridge into XPlane profiles). The round-5 miss
this answers: the 26.1 MB/step/device flagship ``[L, M, word_dim]``
embedding all-gather sat in the tiny-shape leg for two rounds as an
anonymous 306 KiB row nobody could name, so nobody scaled it. Collectives
with NO attribution are now a loud warning and a nonzero exit under
``--strict`` — a payload term can never go uncounted again.

The flagship leg additionally enforces the compact-demb regression gate:
no single collective may move >= L*M*word_dim*4 bytes (the dense
embedding all-gather's size) — the sharding-safe demb path
(parallel/sharding.make_compact_demb_lookup) all-reduces only the compact
[U, D] touched-row gradient. tests/test_comms.py runs the same gate at
tiny shapes in tier-1.

Bytes are per-device per-step at the dryrun's tiny shapes; the ledger also
re-derives the dominant term analytically (gradient allreduce ~= 2x param
bytes for ring allreduce) so BASELINE.md can project to flagship shapes
and v4-8 scale. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/comms_ledger.py [--json out.json] [--strict]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

# `f32[4,128]{1,0}` or scalar `f32[]` — shapes as HLO prints them.
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)


def _shape_bytes(shape_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')

# op_name path components that are trace scaffolding, not provenance.
_SCAFFOLD = frozenset({"while", "body", "cond", "checkpoint", "remat"})


def _attr_label(op_name: str) -> str:
    """jax HLO op_name -> compact source label: direction (fwd/bwd) +
    the meaningful tail of the module/named_scope path.

    ``jit(multi_step)/jit(main)/while/body/transpose(jvp(InductionNetwork))
    /encoder/.../embedding/reshape`` -> ``bwd:.../embedding/reshape``.
    Explicit ``jax.named_scope`` names (e.g. the compact-demb psum's
    ``demb/compact_allreduce``) ride the same path and survive into the
    label — the bridge between obs span vocabulary and HLO metadata."""
    parts = [p for p in op_name.split("/") if p and not p.startswith("jit(")]
    bwd = any(p.startswith("transpose(") for p in parts)
    core = [
        p for p in parts
        if p not in _SCAFFOLD
        and not p.startswith("transpose(")
        and not p.startswith("jvp(")
    ]
    tail = "/".join(core[-3:]) if core else op_name
    return f"{'bwd' if bwd else 'fwd'}:{tail}"


# --- dataflow provenance (round 9) ------------------------------------------
#
# The GSPMD partitioner inserts resharding collectives (moment re-gathers,
# tp/ep/sp layout hops) with NO op_name metadata — they are compiler
# artifacts, not traced ops, so there is nothing to jax.named_scope. Those
# were the four residual attribution-debt legs (zero1 49 KB, dp4_tp2
# 12.7 KB, sp 6.1 KB, ep 1.6 KB — RUNBOOK §12, ROADMAP item 5). But a
# reshard is not anonymous in the DATAFLOW sense: it moves the value some
# attributed op produced. ``collective_rows`` therefore resolves a
# metadata-less collective by walking its operand chain to the nearest
# instruction that DOES carry op_name and labels it
# ``reshard:<that label>`` (marked ``derived``). Only a collective whose
# entire ancestor chain is metadata-free stays ``source=None`` — still a
# loud warning and a --strict failure, so the gate keeps meaning
# "every payload term is nameable", now with zero standing exceptions.

_PROVENANCE_DEPTH = 16


def _instruction_index(hlo_text: str) -> dict[str, tuple[str | None, list[str]]]:
    """Every instruction in every computation: name -> (op_name metadata or
    None, operand instruction names). Instruction names are unique
    module-wide in compiled-HLO printouts, so one flat index serves the
    provenance walk."""
    idx: dict[str, tuple[str | None, list[str]]] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rest = m.groups()
        nm = _OP_NAME_RE.search(line)
        body = rest.split(", metadata=")[0]
        idx[name] = (
            nm.group(1) if nm and nm.group(1) else None,
            _REF_RE.findall(body),
        )
    return idx


def _provenance_label(
    name: str, idx: dict[str, tuple[str | None, list[str]]],
    depth: int = _PROVENANCE_DEPTH,
) -> str | None:
    """BFS the operand chain of instruction ``name`` for the nearest
    op_name; None when every ancestor within ``depth`` is metadata-free."""
    seen = {name}
    frontier = list(idx.get(name, (None, []))[1])
    for _ in range(depth):
        if not frontier:
            return None
        nxt: list[str] = []
        for ref in frontier:
            if ref in seen:
                continue
            seen.add(ref)
            entry = idx.get(ref)
            if entry is None:   # computation ref (calls=...) — dead end
                continue
            op_name, operands = entry
            if op_name:
                return _attr_label(op_name)
            nxt.extend(operands)
        frontier = nxt
    return None


def collective_rows(hlo_text: str) -> list[dict]:
    """HLO text -> one row per collective op: ``{op, bytes, source}`` from
    op OUTPUT shapes (ring all-reduce moves ~2x this on the wire; the
    ledger reports payload bytes and lets the projection apply the
    algorithm factor). ``source`` is the attribution label parsed from the
    op's metadata; a metadata-less collective (GSPMD-inserted reshard)
    resolves through dataflow provenance to ``reshard:<producer label>``
    with ``derived=True``; None only when no ancestor carries metadata —
    an unattributed payload term (see check_attribution)."""
    rows: list[dict] = []
    pending: list[tuple[int, str]] = []   # (row index, instruction name)
    for line in hlo_text.splitlines():
        line = line.strip()
        # Skip fusion/computation headers; match `<shape> <op>(`  e.g.
        # `%ar = f32[128]{0} all-reduce(...)`. Async pairs: the base op is
        # captured LAZILY so `-start`/`-done` land in the suffix group
        # (a greedy `[a-z\-]+` would swallow them and the op-name lookup
        # would silently drop every async collective — review finding,
        # round 5); `-done` ops are skipped, `-start` carries the shape.
        m = re.match(r"%?[\w.\-]+\s*=\s*(\([^)]*\)|[\w\[\],{}: ]+?)\s+"
                     r"([a-z\-]+?)(-start|-done)?\(", line)
        if not m:
            continue
        shape_str, op, suffix = m.groups()
        if op not in _COLLECTIVES or suffix == "-done":
            continue
        nm = _OP_NAME_RE.search(line)
        rows.append({
            "op": op,
            "bytes": _shape_bytes(shape_str),
            "source": _attr_label(nm.group(1)) if nm and nm.group(1) else None,
            # The backend compiled this collective as an async start/done
            # pair (the spelling the latency-hiding scheduler overlaps);
            # CPU emits sync ops, TPU splits eligible collectives.
            "async": suffix == "-start",
        })
        if rows[-1]["source"] is None:
            im = _INSTR_RE.match(line)
            if im:
                pending.append((len(rows) - 1, im.group(1)))
    if pending:
        idx = _instruction_index(hlo_text)
        for row_i, name in pending:
            label = _provenance_label(name, idx)
            if label is not None:
                rows[row_i]["source"] = f"reshard:{label}"
                rows[row_i]["derived"] = True
    return rows


# --- overlap budget (round 8) ----------------------------------------------
#
# The compact-demb restructure (parallel/sharding.make_compact_demb_lookup)
# moved the [U, D] all-reduce out of the shard_map body: the region now
# emits per-shard partials (start) and the reduction is a free-floating
# sum whose only consumer is the word-table update (done). Whether the
# runtime actually hides the reduction is a chip question (the async
# start/done spelling above, queued A/B in BASELINE round 8) — but the
# SCHEDULING FREEDOM the restructure buys is a dataflow property of the
# compiled module, checkable on any backend: of the instructions scheduled
# after the collective, how many do NOT transitively depend on it (the
# latency-hiding window) vs how many do (its consumer chain).

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_REF_RE = re.compile(r"%([\w.\-]+)")

# Computation header: unindented `ENTRY %main (...) -> ... {` or
# `%region_0.24 (...) -> ... {` (compiled printouts; the `%` is optional
# in some older spellings).
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\{\s*$")

# `= <shape> <op>(-start|-done)?(` on an instruction line — the same lazy
# op match collective_rows uses, factored so the window walkers agree.
_OP_OF_LINE_RE = re.compile(
    r"=\s*(?:\([^)]*\)|[\w\[\],{}: ]+?)\s+([a-z\-]+?)(-start|-done)?\("
)

# The output shape of any instruction line (tuple or array spelling) —
# feeds _shape_bytes so every instruction in a window carries its bytes.
_OUT_SHAPE_RE = re.compile(
    r"=\s*((?:\([^)]*\))|(?:[\w\[\],{}: ]+?))\s+[a-z][\w\-]*\("
)

# No-cost instructions: aliases and graph plumbing, not HBM work — their
# "output bytes" must not inflate a dataflow window (the while-body's
# single tuple parameter alone aliases the whole carried train state,
# ~100 MB at the flagship shape, none of it traffic).
_FREE_OPS = frozenset({
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "iota", "after-all",
})

# Collective participant count, parsed from the op's own replica_groups:
# explicit `replica_groups={{0,1,...},...}` (group size = first group's
# element count) or iota `replica_groups=[G,S]<=[N]` (S per group). This
# is what makes the wire factor honest on mixed meshes — a tp=2 reshard
# on the dp4_tp2 leg prices at d=2, not the mesh's 8.
_GROUPS_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2))
    m = _GROUPS_RE.search(line)
    if m:
        return len([t for t in m.group(1).split(",") if t.strip()])
    return default


def _computation_instructions(
    hlo_text: str,
) -> dict[str, list[tuple[str, set, str, int]]]:
    """Every computation's instruction list, in printed (scheduled, for
    compiled modules) order: {computation name: [(name, operands, line,
    out_bytes)]}. The ENTRY computation is additionally keyed "ENTRY" —
    collectives in a fused scan live in the while BODY computation, so the
    whole-step walkers must see every computation, not just ENTRY."""
    out: dict[str, list[tuple[str, set, str, int]]] = {}
    current: list[tuple[str, set, str, int]] | None = None
    for line in hlo_text.splitlines():
        if not line.startswith((" ", "\t")):
            cm = _COMP_RE.match(line)
            if cm:
                current = out.setdefault(cm.group(2), [])
                if cm.group(1):
                    out["ENTRY"] = current
                continue
            if line.startswith("}"):
                current = None
            continue
        if current is None:
            continue
        m = _INSTR_RE.match(line)
        if m:
            name, rest = m.groups()
            # Strip metadata before collecting %refs — op_name paths
            # can contain %-free text only, but stay safe.
            body = rest.split(", metadata=")[0]
            sm = _OUT_SHAPE_RE.search(line)
            nbytes = _shape_bytes(sm.group(1)) if sm else 0
            om = _OP_OF_LINE_RE.search(line)
            if om and om.group(1) in _FREE_OPS:
                nbytes = 0
            current.append((
                name, set(_REF_RE.findall(body)), line, nbytes,
            ))
    return out


def _entry_instructions(hlo_text: str) -> list[tuple[str, set, str, int]]:
    """The ENTRY computation's instruction list, in printed (scheduled,
    for compiled modules) order: [(name, operand_names, line, out_bytes)]."""
    return _computation_instructions(hlo_text).get("ENTRY", [])


def _window_after(
    instrs: list[tuple[str, set, str, int]], idx: int
) -> tuple[int, int, int, int]:
    """(dependent ops, independent ops, dependent bytes, independent
    bytes) for the instruction at position ``idx`` in one computation.

    Dependent = its transitive CONSUMERS (all print after it in scheduled
    SSA order — the chain that must wait for the collective). Independent
    = every instruction that is neither a transitive consumer nor a
    transitive PRODUCER: the set a latency-hiding scheduler may run while
    the collective is in flight, regardless of where the sequential
    printout happened to place it. Counting only later-printed
    instructions (the round-8 spelling) under-measured exactly the
    restructure this ledger gates: the CPU scheduler, which has no
    latency hiding, prints a free-floating bucket psum right before its
    consumer, hiding the earlier-printed backward work the psum does NOT
    depend on. Dataflow, not print position, is the backend-honest
    property. The byte sides sum each instruction's output bytes (the
    HBM-write proxy the round-10 cost model prices against wire time).
    For an async ``-start`` the seed is the start op, so the ``-done``
    and everything it feeds count as dependent — both spellings measure
    the same dataflow window."""
    by_name = {
        name: operands for name, operands, _, _ in instrs
    }
    seed = instrs[idx][0]
    dependents = {seed}
    dep_after = dep_bytes = 0
    for later_name, operands, _, nbytes in instrs[idx + 1:]:
        if operands & dependents:
            dependents.add(later_name)
            dep_after += 1
            dep_bytes += nbytes
    ancestors: set = set()
    frontier = list(instrs[idx][1])
    while frontier:
        n = frontier.pop()
        if n in ancestors or n not in by_name:
            continue
        ancestors.add(n)
        frontier.extend(by_name[n])
    indep = indep_bytes = 0
    for name, _, _, nbytes in instrs:
        if name in dependents or name in ancestors:
            continue
        indep += 1
        indep_bytes += nbytes
    return dep_after, indep, dep_bytes, indep_bytes


def overlap_rows(hlo_text: str, participants: int = 8) -> list[dict]:
    """The WHOLE-STEP overlap ledger (round 10): one row per collective in
    the compiled module — every computation, not just ENTRY — with its
    dataflow window in the printed (scheduled) order:

    ``{op, kind, bytes, wire_bytes, group_size, source, async,
    dependent_ops_after, independent_ops_after, dependent_bytes_after,
    independent_bytes_after, overlap_frac, op_window_frac}``

    ``overlap_frac`` is the roofline cost model: the collective takes
    ``wire_bytes / NOMINAL_V5E_ICI`` seconds on the interconnect
    (ring-factor wire bytes at the op's OWN replica-group size), and the
    independent window after it — later instructions that do not
    transitively consume its result — represents
    ``independent_bytes_after / NOMINAL_V5E_BW`` seconds of HBM-bound
    compute a latency-hiding scheduler can run concurrently. The fraction
    of wire time covered, clamped to 1.0, is the row's overlap. One home
    for every constant: utils/roofline (NOMINAL_V5E_BW/ICI, ring_factor).

    ``op_window_frac`` = independent / (independent + dependent) op
    counts — the round-8 structural diagnostic, kept because it shows WHY
    a window is small (the global-norm clip couples every grad all-reduce
    to the whole Adam/update tail, a ~64-op dependent chain the op count
    exposes and the byte model correctly prices as cheap). Round 8
    measured one hand-picked demb fragment; this walks every attributed
    collective so the "~22% un-overlapped" headline becomes a measured,
    per-leg number (overlap_summary)."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        NOMINAL_V5E_BW,
        NOMINAL_V5E_ICI,
        ring_factor,
    )

    comps = _computation_instructions(hlo_text)
    rows: list[dict] = []
    pending: list[tuple[int, str]] = []
    for comp_name, instrs in comps.items():
        if comp_name == "ENTRY":
            # Alias of the entry computation's own named key — skipping it
            # keeps every collective counted exactly once.
            continue
        for i, (name, _, line, _nb) in enumerate(instrs):
            m = _OP_OF_LINE_RE.search(line)
            if not m:
                continue
            kind, suffix = m.group(1), m.group(2)
            if kind not in _COLLECTIVES or suffix == "-done":
                continue
            dep, indep, dep_b, indep_b = _window_after(instrs, i)
            nm = _OP_NAME_RE.search(line)
            shape_str = line.split("=", 1)[1]
            payload = _shape_bytes(shape_str.split(kind)[0])
            d = _group_size(line, participants)
            wire = payload * ring_factor(kind, d)
            if wire > 0:
                covered = (indep_b / NOMINAL_V5E_BW) / (wire / NOMINAL_V5E_ICI)
                frac = min(1.0, covered)
            else:
                frac = 1.0   # degenerate single-participant group: no wire
            rows.append({
                "op": name,
                "kind": kind,
                "bytes": payload,
                "wire_bytes": int(wire),
                "group_size": d,
                "source": (
                    _attr_label(nm.group(1)) if nm and nm.group(1) else None
                ),
                "async": suffix == "-start",
                "dependent_ops_after": dep,
                "independent_ops_after": indep,
                "dependent_bytes_after": dep_b,
                "independent_bytes_after": indep_b,
                "overlap_frac": round(frac, 4),
                "op_window_frac": (
                    round(indep / (indep + dep), 4) if (indep + dep) else 0.0
                ),
            })
            if rows[-1]["source"] is None:
                pending.append((len(rows) - 1, name))
    if pending:
        idx = _instruction_index(hlo_text)
        for row_i, name in pending:
            label = _provenance_label(name, idx)
            if label is not None:
                rows[row_i]["source"] = f"reshard:{label}"
                rows[row_i]["derived"] = True
    rows.sort(key=lambda r: -r["wire_bytes"])
    return rows


def overlap_summary(hlo_text: str, participants: int = 8) -> dict:
    """Wire-bytes-weighted overlap headline for one compiled module:

    ``{collectives: [overlap_rows...], total_bytes, total_wire_bytes,
    overlap_frac, unoverlapped_frac, op_window_frac, async_collectives}``

    ``overlap_frac`` weights each collective's cost-model coverage by its
    WIRE bytes — Σ wire·frac / Σ wire — so one big barriered all-reduce
    cannot hide behind many tiny free-floating ones, and an all-reduce
    (2(d-1)/d on the wire) outweighs an equal-payload permute.
    ``unoverlapped_frac`` (1 − overlap_frac) replaces the hand-derived
    "~22%" from COMMS_r06: the regression-gated number COMMS_r10.json
    commits per leg. ``op_window_frac`` is the same weighting of the
    round-8 op-count diagnostic."""
    rows = overlap_rows(hlo_text, participants)
    total = sum(r["bytes"] for r in rows)
    wire = sum(r["wire_bytes"] for r in rows)
    weighted = (
        sum(r["wire_bytes"] * r["overlap_frac"] for r in rows) / wire
        if wire else 1.0
    )
    op_weighted = (
        sum(r["wire_bytes"] * r["op_window_frac"] for r in rows) / wire
        if wire else 1.0
    )
    return {
        "collectives": rows,
        "total_bytes": total,
        "total_wire_bytes": wire,
        "overlap_frac": round(weighted, 4),
        "unoverlapped_frac": round(1.0 - weighted, 4),
        "op_window_frac": round(op_weighted, 4),
        "async_collectives": sum(1 for r in rows if r["async"]),
    }


def overlap_report(
    hlo_text: str, source_frag: str = "demb/compact_allreduce"
) -> dict | None:
    """Overlap budget of the collective attributed to ``source_frag``:
    {op, dependent_ops_after, independent_ops_after, async} — the
    instructions scheduled after it that its result does/does not feed.
    ``independent_ops_after`` is the window a latency-hiding scheduler
    can fill while the reduction is in flight; ``dependent_ops_after``
    should stay small (the table-update chain). None when no collective
    carries the fragment. Kept as the round-8 single-fragment probe;
    overlap_rows/overlap_summary are the whole-step generalization."""
    for comp in _computation_instructions(hlo_text).values():
        for i, (name, _, line, _nb) in enumerate(comp):
            if source_frag not in line:
                continue
            m = _OP_OF_LINE_RE.search(line)
            if not (m and m.group(1) in _COLLECTIVES
                    and m.group(2) != "-done"):
                continue
            dep, indep, dep_b, indep_b = _window_after(comp, i)
            return {
                "op": name,
                "dependent_ops_after": dep,
                "independent_ops_after": indep,
                "dependent_bytes_after": dep_b,
                "independent_bytes_after": indep_b,
                "async": "-start(" in line,
            }
    return None


def per_op_from_rows(rows: list[dict]) -> dict[str, dict[str, int]]:
    """collective_rows -> {collective op kind: {count, bytes}} — the ONE
    aggregation both collective_bytes and main() use."""
    out: dict[str, dict[str, int]] = {}
    for row in rows:
        entry = out.setdefault(row["op"], {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += row["bytes"]
    return out


def collective_bytes(hlo_text: str) -> dict[str, dict[str, int]]:
    """HLO text -> {collective op kind: {count, bytes}} (see
    collective_rows for the per-op attributed form)."""
    return per_op_from_rows(collective_rows(hlo_text))


def attributed_rows(rows: list[dict]) -> list[dict]:
    """Aggregate collective_rows by (op, source) -> [{op, source, count,
    bytes}], largest payload first. Unattributed rows aggregate under
    source=None so they stay visible, never silently merged."""
    agg: dict[tuple, dict] = {}
    for r in rows:
        key = (r["op"], r["source"])
        e = agg.setdefault(
            key, {"op": r["op"], "source": r["source"], "count": 0, "bytes": 0}
        )
        e["count"] += 1
        e["bytes"] += r["bytes"]
    return sorted(agg.values(), key=lambda e: -e["bytes"])


def check_attribution(name: str, rows: list[dict]) -> int:
    """Count unattributed collective bytes; print a LOUD warning when any
    exist (the round-5 failure mode: the 306 KiB anonymous all-gather that
    became 26 MB at the flagship shape). Returns the unattributed byte
    count — main() turns it into a nonzero exit under --strict."""
    anon = [r for r in rows if r["source"] is None]
    anon_bytes = sum(r["bytes"] for r in anon)
    if anon:
        print(
            f"WARNING [{name}]: {len(anon)} unattributed collective(s), "
            f"{anon_bytes} B/step/device with no op_name metadata — every "
            "payload term must be nameable (round-5 lesson: the anonymous "
            "306 KiB all-gather was the 26 MB flagship term). Inspect the "
            "compiled HLO; add a jax.named_scope at the producing op.",
            file=sys.stderr,
        )
    return anon_bytes


def _tiny(**kw):
    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    base = dict(
        encoder="bilstm", train_n=3, n=3, k=2, q=2, batch_size=8,
        max_length=16, vocab_size=302, compute_dtype="float32",
        lstm_hidden=32, att_dim=16, induction_dim=32, ntn_slices=16,
    )
    base.update(kw)
    return ExperimentConfig(**base)


def _legs():
    """[(name, cfg, make mesh, build step+args)] — mirrors the dryrun legs."""
    import jax

    import __graft_entry__ as ge
    from induction_network_on_fewrel_tpu.parallel import make_mesh
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        demb_impl_for,
        make_sharded_train_step,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    def plain(cfg, mesh):
        model, params, sup, qry, label = ge._build(
            cfg, demb_impl=demb_impl_for(cfg, mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    legs = []

    cfg = _tiny(dp=8)
    legs.append(("dp8", cfg, make_mesh(dp=8), plain))

    # Bucketed arm of the same leg (round 10): the dense-param gradient
    # psum split into named reverse-topological buckets, hoisted the way
    # the compact-demb psum was in round 8 — each bucket's all-reduce is
    # a free-floating attributed op (grad/bucket_k) the overlap walker
    # can price individually. "on" forces the TPU-resolved default onto
    # the CPU ledger mesh; the monolithic dp8 leg above is its control.
    cfg = _tiny(dp=8, grad_bucketing="on")
    legs.append(("dp8_bucketed", cfg, make_mesh(dp=8), plain))

    cfg = _tiny(dp=4, tp=2)
    legs.append(("dp4_tp2", cfg, make_mesh(dp=4, tp=2), plain))

    cfg = _tiny(dp=8, zero_opt=True)
    legs.append(("dp8_zero1", cfg, make_mesh(dp=8), plain))

    def sp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.ring import (
            make_ring_attention,
        )

        model, params, sup, qry, label = ge._build(
            cfg, attn_impl=make_ring_attention(mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, dp=2, sp=4,
                batch_size=2)
    legs.append(("dp2_sp4_ring", cfg, make_mesh(dp=2, sp=4), sp_leg))

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=2,
                tfm_model=32, tfm_heads=2, tfm_ff=64, moe_experts=4,
                moe_top_k=2, moe_every=2, dp=2, ep=4, batch_size=2)
    legs.append(("dp2_ep4_moe", cfg, make_mesh(dp=2, ep=4), plain))

    def pp_leg(cfg, mesh):
        from induction_network_on_fewrel_tpu.parallel.pipeline import (
            make_gpipe,
        )

        gp = make_gpipe(mesh, microbatches=cfg.pp_microbatches,
                        batch_axis="dp" if mesh.shape["dp"] > 1 else None)
        model, params, sup, qry, label = ge._build(
            cfg, pipeline_impl=gp, demb_impl=demb_impl_for(cfg, mesh)
        )
        state = init_state(model, cfg, sup, qry)
        step = make_sharded_train_step(model, cfg, mesh, state)
        return step, (state, sup, qry, label)

    cfg = _tiny(model="proto", encoder="transformer", tfm_layers=4,
                tfm_model=32, tfm_heads=2, tfm_ff=64, tfm_stacked=True,
                dp=2, pp=4, pp_microbatches=2, batch_size=4)
    legs.append(("dp2_pp4_gpipe", cfg, make_mesh(dp=2, pp=4), pp_leg))

    # steps_per_call=1 deliberately: a fused scan's in-loop collectives
    # print ONCE in static HLO but execute per iteration — dividing a
    # static count by S would undercount (review finding, round 5). The
    # S=1 compile gives the exact per-step bytes of the same body.
    cfg = _tiny(dp=8, token_cache=True, steps_per_call=1,
                embed_optimizer="lazy")
    legs.append(("dp8_tokencache_lazy", cfg, make_mesh(dp=8), _cached_leg))

    # Bucketed arm of the production path at tiny shapes — the same body
    # the flagship leg compiles at the real shape, so tier-1
    # (tests/test_comms.py) can gate the overlap headline without the
    # minutes-long flagship compile.
    cfg = _tiny(dp=8, token_cache=True, steps_per_call=1,
                embed_optimizer="lazy", grad_bucketing="on")
    legs.append(("dp8_lazy_bucketed", cfg, make_mesh(dp=8), _cached_leg))

    return legs


def _cached_leg(cfg, mesh):
    """Build the token-cache lazy fused step (any shape: the tiny dryrun
    leg AND the flagship leg share this builder; the corpus stays small —
    the table's 400k rows, not the sentences, are what scale)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        augment_token_table,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_train_step,
        tokenize_dataset,
    )

    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=max(6, cfg.n + 1),
        instances_per_relation=cfg.k + cfg.q + 2,
        vocab_size=min(cfg.vocab_size - 2, 2000),
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    if cfg.embed_optimizer == "lazy":
        table_np, uids = augment_token_table(table_np)
        table_np = {**table_np, "uids": uids}
    table = {
        k: jax.device_put(v, NamedSharding(mesh, PartitionSpec()))
        for k, v in table_np.items()
    }
    idx = make_index_sampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0,
        backend="python",
    )
    from induction_network_on_fewrel_tpu.parallel.sharding import (
        demb_impl_for,
    )

    model = build_model(
        cfg, glove_init=vocab.vectors, demb_impl=demb_impl_for(cfg, mesh)
    )
    si, qi, lab = idx.sample_fused(cfg.steps_per_call)
    sup = {k: v[si[0]] for k, v in table_np.items() if k != "uids"}
    qry = {k: v[qi[0]] for k, v in table_np.items() if k != "uids"}
    state = init_state(model, cfg, sup, qry)
    step = make_token_cached_multi_train_step(model, cfg, mesh, state)
    return step, (state, table, si, qi, lab)


# Round-5's projection (BASELINE.md comms section) modeled ONLY the dp
# gradient all-reduce: non-embedding grads ~5.05 MB f32 + compact
# lazy-row cotangent ~0.4 MB => 5.45 MB payload, 10.7 MB ring wire. The
# round-6 flagship compile REFUTED it: the partitioned HLO additionally
# all-gathered the full [L, M, word_dim] f32 embedding across dp
# (25.6 MB/step/device at the flagship shape — present in the round-5
# tiny-shape leg all along as its UNATTRIBUTED 306 KiB all-gather, just
# never scaled up) plus ~2 MB of resharding permutes. Round 7 removed
# the all-gather (parallel/sharding.make_compact_demb_lookup: the demb
# segment-sum stays local per shard; only the compact [U, D] touched-row
# gradient is all-reduced — already inside the 5.45 MB grad term), so
# the projection is back to the round-5 shape PLUS the resharding term
# the round-6 compile taught us to count. With every collective now
# attributed (collective_rows) the band tightens from ±40% to ±15%: the
# wide band existed only because a 26 MB term was anonymous. The same
# formulas live in utils/roofline.comms_components so bench.py's
# comms_bytes_per_step and this assertion can never drift apart.


def flagship_payload_projection(cfg) -> float:
    """Round-7 payload model: grad all-reduce (non-embedding grads + the
    compact [U, D] demb rows) + resharding slack. The [L, M, word_dim]
    all-gather is structurally absent — enforced by check_flagship's
    regression gate, not just this band."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        comms_payload_bytes,
    )

    return comms_payload_bytes(cfg)


def flagship_leg():
    """(name, cfg, mesh, build) for the REAL-shape production path:
    vocab 400,002, B=64, L=40, token-cache lazy, dp=8."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.parallel import make_mesh

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=64, max_length=40,
        vocab_size=400002, compute_dtype="bfloat16", dp=8,
        token_cache=True, steps_per_call=1, embed_optimizer="lazy",
        # Round 10: the production arm ships the bucketed gradient
        # collectives (what "auto" resolves to on TPU) — the overlap
        # headline check_flagship gates is measured on THIS spelling.
        grad_bucketing="on",
    )
    return ("dp8_tokencache_lazy_flagship", cfg, make_mesh(dp=8), _cached_leg)


def dense_allgather_bytes(cfg) -> int:
    """The regression-gate threshold: the dense [L, M, word_dim] f32
    embedding all-gather's payload at cfg's shape. No single collective
    may reach it — if one does, a sharding change silently reintroduced
    the replicated embedding (the 26 MB round-6 finding). One home for
    the arithmetic: utils/roofline.dense_embedding_allgather_bytes."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        dense_embedding_allgather_bytes,
    )

    return dense_embedding_allgather_bytes(cfg)


def check_flagship(cfg, result: dict, tol: float = 0.15) -> None:
    """Assert (a) the compiled flagship payload is within ``tol`` of the
    projection and (b) NO single collective moves >= the dense embedding
    all-gather's bytes (the compact-demb regression gate). The band
    tightened from the round-6 ±40% to ±15%: it was wide only because
    the dominant term was unattributed — with per-collective attribution
    the model's terms are nameable against compiled rows one by one."""
    from induction_network_on_fewrel_tpu.utils.roofline import (
        comms_components,
    )

    total = result["total_bytes_per_step_per_device"]
    proj = flagship_payload_projection(cfg)
    terms = "; ".join(
        f"{name} {b / 1e6:.2f}" for name, b in comms_components(cfg)
    )
    lo, hi = proj * (1 - tol), proj * (1 + tol)
    assert lo <= total <= hi, (
        f"flagship collective payload {total / 1e6:.2f} MB/step/device "
        f"outside [{lo / 1e6:.2f}, {hi / 1e6:.2f}] — the round-7 "
        f"projection ({proj / 1e6:.2f} MB payload: {terms}) no longer "
        "describes what GSPMD schedules at the real shape"
    )
    gate = dense_allgather_bytes(cfg)
    worst = max(
        (r for r in result.get("attributed", [{"bytes": 0}])),
        key=lambda r: r["bytes"] // max(r.get("count", 1), 1),
        default={"bytes": 0},
    )
    biggest = max((r["bytes"] for r in result.get("rows", [])), default=0)
    assert biggest < gate, (
        f"REGRESSION: a single collective moves {biggest} B >= the dense "
        f"[L,M,word_dim] embedding all-gather ({gate} B) — a sharding "
        f"change reintroduced the replicated embedding (worst row: "
        f"{worst}). See parallel/sharding.make_compact_demb_lookup."
    )
    # Wire estimate from the shared ring-factor model (ONE home:
    # utils/roofline.wire_bytes), at the leg's actual dp.
    from induction_network_on_fewrel_tpu.utils.roofline import wire_bytes

    ar = sum(
        v["bytes"] for k, v in result["collectives"].items()
        if k in ("all-reduce", "reduce-scatter")
    )
    ag = result["collectives"].get("all-gather", {}).get("bytes", 0)
    wire = wire_bytes(
        {"all-reduce": ar, "all-gather": ag, "other": total - ar - ag},
        result["mesh"].get("dp", 8),
    )
    # Round-10 flagship overlap gate: with the gradient psums bucketed
    # (grad/bucket_k) the wire-weighted un-overlapped share by the
    # dataflow-window cost model must stay <= 8% — the measured successor
    # to the hand-derived "~22%" COMMS_r06 figure. Regression direction
    # only: a sharding/bucketing change that re-barriers the collectives
    # fails here before it ships.
    ov = result.get("overlap")
    if ov is not None:
        assert ov["unoverlapped_frac"] <= 0.08, (
            f"flagship un-overlapped share {ov['unoverlapped_frac']:.1%} "
            "> 8% — a collective lost its independent window (re-barriered "
            "grad psum? bucket collapsed into the norm/update chain?). "
            "Worst rows: "
            + "; ".join(
                f"{r['source']} frac={r['overlap_frac']}"
                for r in sorted(
                    ov["collectives"], key=lambda r: r["overlap_frac"]
                )[:3]
            )
        )
    print(
        f"flagship: payload {total / 1e6:.2f} MB/step/device (projection "
        f"{proj / 1e6:.2f}, within {tol:.0%}); wire ~{wire / 1e6:.1f} MB; "
        f"un-overlapped {ov['unoverlapped_frac']:.1%} by the "
        "dataflow-window cost model (was a hand-derived ~22% before the "
        "compact-demb + bucketed-grad restructures, COMMS_r06)"
        if ov is not None else
        f"flagship: payload {total / 1e6:.2f} MB/step/device (projection "
        f"{proj / 1e6:.2f}, within {tol:.0%}); wire ~{wire / 1e6:.1f} MB"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument(
        "--skip-flagship", action="store_true",
        help="skip the real-shape (vocab 400,002, B=64) flagship leg — "
             "it compiles the production fused step, which takes minutes "
             "on small hosts",
    )
    ap.add_argument(
        "--only-flagship", action="store_true",
        help="run ONLY the flagship leg + its projection assertion",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit nonzero if ANY collective lacks op_name attribution — "
             "an anonymous payload term is how the 26 MB flagship "
             "all-gather hid for two rounds",
    )
    ap.add_argument(
        "--legs", default=None,
        help="comma-separated dryrun-leg names to run (default: all). "
             "tests/test_comms.py uses this to keep the tier-1 strict "
             "sweep on the four GSPMD-reshard debt legs + gpipe while "
             "the dp8/bucketed/lazy legs are gated by their own compiled "
             "tier-1 tests; the committed COMMS_r*.json artifacts always "
             "run the full set",
    )
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    import jax

    if "xla_force_host_platform_device_count" in os.environ["XLA_FLAGS"]:
        jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= 8, "need 8 virtual devices"

    def param_count(params) -> int:
        return sum(x.size for x in jax.tree.leaves(params))

    legs = [] if args.only_flagship else _legs()
    if args.legs is not None:
        want = {w.strip() for w in args.legs.split(",") if w.strip()}
        known = {name for name, *_ in legs}
        unknown = want - known
        assert not unknown, f"unknown --legs {sorted(unknown)}; have {sorted(known)}"
        legs = [leg for leg in legs if leg[0] in want]
    if not args.skip_flagship:
        legs.append(flagship_leg())

    results = {}
    anon_total = 0
    for name, cfg, mesh, build in legs:
        step, fn_args = build(cfg, mesh)
        lowered = step.lower(*fn_args)
        compiled = lowered.compile()
        hlo_text = compiled.as_text()
        rows = collective_rows(hlo_text)
        attributed = attributed_rows(rows)
        anon_total += check_attribution(name, rows)
        per_op = per_op_from_rows(rows)
        total = sum(v["bytes"] for v in per_op.values())
        n_params = None
        try:
            n_params = param_count(fn_args[0].params)
        except Exception:
            pass
        results[name] = {
            "mesh": dict(mesh.shape),
            "collectives": per_op,
            "attributed": attributed,
            "unattributed_bytes": sum(
                r["bytes"] for r in rows if r["source"] is None
            ),
            "async_collectives": sum(1 for r in rows if r.get("async")),
            "total_bytes_per_step_per_device": total,
            "param_count": n_params,
            "param_bytes_f32": (4 * n_params) if n_params else None,
        }
        overlap = overlap_report(hlo_text)
        if overlap is not None:
            # Round-8 overlap restructure: the demb all-reduce floats free
            # between the per-shard partials and the table update — record
            # the dataflow window a latency-hiding scheduler has.
            results[name]["demb_overlap"] = overlap
        # Round 10: the whole-step overlap ledger — every collective's
        # dataflow window priced by the roofline cost model, wire-weighted
        # into one regression-gated headline per leg.
        results[name]["overlap"] = overlap_summary(
            hlo_text, participants=int(mesh.devices.size)
        )
        print(f"{name}: {total} B/step/device, "
              f"{ {k: v['count'] for k, v in per_op.items()} }")
        for row in attributed[:6]:
            print(f"  {row['bytes']:>10} B x{row['count']:<3} {row['op']:<19} "
                  f"{row['source'] or 'UNATTRIBUTED'}")
        if overlap is not None:
            print(
                f"  demb overlap window: {overlap['independent_ops_after']} "
                f"independent ops schedulable during the reduction, "
                f"{overlap['dependent_ops_after']} dependent (table-update "
                f"chain); async spelling: {overlap['async']}"
            )
        ov = results[name]["overlap"]
        print(
            f"  overlap: {ov['overlap_frac']:.1%} of "
            f"{ov['total_wire_bytes'] / 1e3:.1f} KB wire covered "
            f"(un-overlapped {ov['unoverlapped_frac']:.1%}; op-window "
            f"diag {ov['op_window_frac']:.1%}; "
            f"{len(ov['collectives'])} collectives)"
        )
        for row in ov["collectives"][:4]:
            print(
                f"    {row['wire_bytes']:>10} B wire  frac "
                f"{row['overlap_frac']:<6.4f} {row['kind']:<19} "
                f"{row['source'] or 'UNATTRIBUTED'}"
            )
        if name == "dp8_tokencache_lazy_flagship":
            # VERDICT round-5 item 5: the projection must describe what
            # GSPMD actually schedules at the REAL shape, asserted here —
            # plus the round-7 regression gate (no dense-sized collective).
            results[name]["rows"] = rows
            check_flagship(cfg, results[name])
            del results[name]["rows"]
            results[name]["payload_projection_bytes"] = (
                flagship_payload_projection(cfg)
            )
            results[name]["dense_allgather_gate_bytes"] = (
                dense_allgather_bytes(cfg)
            )

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {args.json}")
    if args.strict and anon_total:
        print(f"--strict: {anon_total} unattributed collective bytes",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
