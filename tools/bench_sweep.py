#!/usr/bin/env python3
"""Throughput sweep over the BASELINE.json benchmark configs.

Same chunked, HARD-SYNCED methodology as bench.py: every chunk ends with a
device_get of the loss scalar, because on this tunneled backend
``block_until_ready`` does not actually wait for execution (see bench.py's
docstring — block-based timings measure dispatch, not training). One JSON
line per config on stdout; bench.py stays the single-line driver contract,
this is the full table for BASELINE.md.

INTERLEAVED A/B (round-2 VERDICT weak item 3): configs are timed in
GROUPS — a live config and its token-cache twin (or the embed-optimizer
variants) alternate chunks within ONE tunnel session, so a difference
between rows in a group is a real effect, not tunnel weather. Each row
reports median ± spread over its chunks, not just the best.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BATCH = 8
WARMUP = 5
CHUNK = 20
ROUNDS = 5  # interleaved chunks per config per group
MAX_SECONDS = 45.0  # per config within a group


def prepare_config(name: str, cfg, adv: bool = False, mode: str = "train"):
    import jax

    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.data.bert_tokenizer import BertTokenizer
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.adversarial import (
        DomainDiscriminator,
    )
    from induction_network_on_fewrel_tpu.models.build import (
        batch_to_model_inputs,
        encoder_output_dim,
    )
    from induction_network_on_fewrel_tpu.native import make_sampler
    from induction_network_on_fewrel_tpu.sampling import InstanceSampler
    from induction_network_on_fewrel_tpu.train.steps import (
        init_disc_state,
        init_state,
        make_adv_train_step,
        make_train_step,
    )

    ds = make_synthetic_fewrel(
        num_relations=max(2 * cfg.n, 20),
        instances_per_relation=cfg.k + cfg.q + 5,
        vocab_size=cfg.vocab_size - 2,
    )
    if cfg.encoder == "bert":
        vocab = None
        tok = BertTokenizer(cfg.max_length, vocab_size=cfg.bert_vocab_size)
    else:
        vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
        tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = make_sampler(
        ds, tok, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
        na_rate=cfg.na_rate, seed=0, backend="auto", prefetch=16, num_threads=4,
    )
    model = build_model(
        cfg, glove_init=vocab.vectors if vocab is not None else None
    )
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    if mode == "eval":
        # EVAL-path throughput (round-5 VERDICT item 6): the fused eval —
        # params fixed, lax.map over S stacked batches — on the cached and
        # live transports. metrics["loss"] is stacked [S], so the shared
        # hard-sync works unchanged.
        from induction_network_on_fewrel_tpu.train.steps import (
            init_state as _init_state,
        )

        S = max(cfg.steps_per_call, 1)
        if cfg.token_cache:
            from induction_network_on_fewrel_tpu.native.sampler import (
                make_index_sampler,
            )
            from induction_network_on_fewrel_tpu.train.token_cache import (
                make_token_cached_multi_eval_step,
                tokenize_dataset,
            )

            if hasattr(sampler, "close"):
                sampler.close()
            table_np, sizes = tokenize_dataset(ds, tok)
            table = jax.device_put(table_np)
            isampler = make_index_sampler(
                sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
                na_rate=cfg.na_rate, seed=0,
            )
            params = _init_state(model, cfg, sup, qry).params
            ev = make_token_cached_multi_eval_step(model, cfg)

            def step_once(params):
                si, qi, ls = isampler.sample_fused(S)
                return params, ev(params, table, si, qi, ls)

            return _prepared(name, cfg, step_once, params, eff=S,
                             closers=[isampler], mode="eval")
        import numpy as np

        from induction_network_on_fewrel_tpu.train.steps import (
            make_multi_eval_step,
        )

        params = _init_state(model, cfg, sup, qry).params
        ev = make_multi_eval_step(model, cfg)

        def step_once(params):
            bs = [batch_to_model_inputs(sampler.sample_batch())
                  for _ in range(S)]
            ss, qs, ls = jax.tree.map(lambda *xs: np.stack(xs), *bs)
            return params, ev(params, ss, qs, ls)

        closers = [sampler] if hasattr(sampler, "close") else []
        return _prepared(name, cfg, step_once, params, eff=S,
                         closers=closers, mode="eval")
    if cfg.token_cache:
        # Device-resident token table + index episodes, fused scan — the
        # production --token_cache path (train/token_cache.py).
        from induction_network_on_fewrel_tpu.native.sampler import (
            make_index_sampler,
        )
        from induction_network_on_fewrel_tpu.train.token_cache import (
            make_token_cached_multi_train_step,
            tokenize_dataset,
        )

        if hasattr(sampler, "close"):
            sampler.close()
        table_np, sizes = tokenize_dataset(ds, tok)
        if cfg.embed_optimizer == "lazy":
            from induction_network_on_fewrel_tpu.train.lazy_embed import (
                augment_token_table,
            )

            table_np, uids = augment_token_table(table_np)
            table_np = {**table_np, "uids": uids}
        table = jax.device_put(table_np)
        # Same sampler policy as the production CLI path: C++ index
        # sampler when the toolchain is present.
        isampler = make_index_sampler(
            sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
            na_rate=cfg.na_rate, seed=0,
        )
        state = init_state(model, cfg, sup, qry)
        S = max(cfg.steps_per_call, 1)
        multi = make_token_cached_multi_train_step(model, cfg)

        def step_once(st):
            si, qi, ls = isampler.sample_fused(S)
            return multi(st, table, si, qi, ls)

        return _prepared(name, cfg, step_once, state, eff=S,
                         closers=[isampler])
    if cfg.feature_cache:
        # Index mode: device-resident table, int32 indices per step, fused
        # scan — the production cached path (train/feature_cache.py).
        import numpy as np

        from induction_network_on_fewrel_tpu.train.feature_cache import (
            FeatureEpisodeSampler,
            encode_dataset,
            make_cached_multi_train_step,
        )

        full_params = model.init(jax.random.key(cfg.seed), sup, qry)
        t0 = time.monotonic()
        blocks = encode_dataset(model, full_params, ds, tok)
        cache_s = time.monotonic() - t0
        del full_params
        if hasattr(sampler, "close"):
            sampler.close()
        sampler = FeatureEpisodeSampler(
            blocks, cfg.n, cfg.k, cfg.q, cfg.batch_size,
            na_rate=cfg.na_rate, seed=0, return_indices=True,
        )
        print(json.dumps({"config": name, "cache_build_s": round(cache_s, 2)}),
              file=sys.stderr)
        table = jax.device_put(sampler.table)
        b0 = sampler.sample_batch()
        state = init_state(
            model, cfg, sampler.table[b0.support_idx],
            sampler.table[b0.query_idx],
        )
        S = max(cfg.steps_per_call, 1)
        multi = make_cached_multi_train_step(model, cfg)

        def step_once(st):
            bs = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(S)]
            si, qi, ls = jax.tree.map(lambda *xs: np.stack(xs), *bs)
            st, m = multi(st, table, si, qi, ls)
            return st, m

        return _prepared(name, cfg, step_once, state, eff=S)
    state = init_state(model, cfg, sup, qry)

    if adv:
        tgt_ds = make_synthetic_fewrel(
            num_relations=20, instances_per_relation=cfg.k + cfg.q + 5,
            vocab_size=cfg.vocab_size - 2, seed=97,
        )
        disc = DomainDiscriminator(hidden=cfg.adv_dis_hidden)
        disc_state = init_disc_state(disc, cfg, encoder_output_dim(cfg))
        src_s = InstanceSampler(ds, tok, cfg.adv_batch, seed=31)
        tgt_s = InstanceSampler(tgt_ds, tok, cfg.adv_batch, seed=32)
        if cfg.steps_per_call > 1:
            import numpy as np

            from induction_network_on_fewrel_tpu.train.steps import (
                make_adv_multi_train_step,
            )

            adv_multi = make_adv_multi_train_step(model, disc, cfg)
            S = cfg.steps_per_call

            def step_once(state_pack):
                st, dst = state_pack
                bs = [
                    (*batch_to_model_inputs(sampler.sample_batch()),
                     src_s.sample_batch()._asdict(),
                     tgt_s.sample_batch()._asdict())
                    for _ in range(S)
                ]
                stacked = jax.tree.map(lambda *xs: np.stack(xs), *bs)
                st, dst, m = adv_multi(st, dst, *stacked)
                return (st, dst), m

        else:
            adv_step = make_adv_train_step(model, disc, cfg)

            def step_once(state_pack):
                st, dst = state_pack
                st, dst, m = adv_step(
                    st, dst, *batch_to_model_inputs(sampler.sample_batch()),
                    src_s.sample_batch()._asdict(),
                    tgt_s.sample_batch()._asdict(),
                )
                return (st, dst), m

        pack = (state, disc_state)
    elif cfg.steps_per_call > 1:
        # steps_per_call fusion, same as the production trainer path: the
        # per-call round-trip on this tunneled backend (~6-10 ms) otherwise
        # swamps every per-step config.
        import numpy as np

        from induction_network_on_fewrel_tpu.train.steps import (
            make_multi_train_step,
        )

        multi = make_multi_train_step(model, cfg)
        S = cfg.steps_per_call

        def step_once(st):
            bs = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(S)]
            ss, qs, ls = jax.tree.map(lambda *xs: np.stack(xs), *bs)
            st, m = multi(st, ss, qs, ls)
            return st, m

        pack = state
    else:
        step = make_train_step(model, cfg)

        def step_once(st):
            st, m = step(st, *batch_to_model_inputs(sampler.sample_batch()))
            return st, m

        pack = state

    eff = cfg.steps_per_call if cfg.steps_per_call > 1 else 1
    closers = [sampler] if hasattr(sampler, "close") else []
    return _prepared(name, cfg, step_once, pack, eff=eff, closers=closers)


def _prepared(name, cfg, step_once, pack, eff=1, closers=(), mode="train"):
    return {
        "name": name, "cfg": cfg, "step_once": step_once, "pack": pack,
        "eff": eff, "closers": list(closers), "rates": [], "warmup_s": None,
        "mode": mode,
    }


def _row_mfu(cfg, rates, mode="train"):
    """Median-rate MFU from the generalized analytic FLOPs model
    (utils/flops.train_step_flops — matmul terms only, 3x-forward
    convention, frozen backbones at 1x/0x). None off-TPU or for configs
    the model doesn't cover (the --adv DANN extra pass is uncounted, so
    adversarial rows report the few-shot-only lower bound)."""
    import statistics

    import jax

    from induction_network_on_fewrel_tpu.utils.flops import (
        peak_flops_per_chip,
        train_step_flops,
    )

    if not rates:
        return None
    try:
        peak = peak_flops_per_chip(
            jax.devices()[0].device_kind, cfg.compute_dtype
        )
        if not peak:
            return None
        fl = train_step_flops(cfg)
        per_ep = fl["per_episode"]
        if mode == "eval":
            # Exact forward count, not per_episode/3: frozen-backbone
            # configs already carry enc_mult=1 in the train number, so a
            # /3 would undercount them (review finding, round 5).
            per_ep = fl["forward"] / cfg.batch_size
        return round(statistics.median(rates) * per_ep / peak, 4)
    except Exception:  # noqa: BLE001 — accounting must never sink a row
        return None


def _hard_sync(metrics):
    # A value fetch, NOT block_until_ready: the tunneled backend's block
    # returns before execution finishes (bench.py docstring).
    import jax
    import numpy as np

    _ = float(np.ravel(jax.device_get(metrics["loss"]))[-1])


def _one_chunk(p) -> float:
    """Run one hard-synced chunk of config ``p``; returns eps/s/chip."""
    import jax

    n_chips = max(jax.local_device_count(), 1)
    eff = p["eff"]
    calls = max(CHUNK // eff, 2) if eff > 1 else CHUNK
    t0 = time.monotonic()
    pack = p["pack"]
    for _ in range(calls):
        pack, metrics = p["step_once"](pack)
    _hard_sync(metrics)
    p["pack"] = pack
    return calls * eff * p["cfg"].batch_size / (time.monotonic() - t0) / n_chips


def run_group(members, rounds: int = ROUNDS):
    """Prepare every member, then ALTERNATE chunks across them within this
    one tunnel session (A/B/A/B...), so cross-member differences are real
    effects, not tunnel weather. Emits one JSON row per member with
    median ± spread over its chunks."""
    import gc
    import statistics

    import jax

    def close_member(p):
        for c in p["closers"]:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — best-effort release
                pass
        p["closers"] = []

    prepared = []
    for member in members:
        name, cfg, adv, mode = (*member, "train")[:4]
        p = None
        try:
            p = prepare_config(name, cfg, adv, mode)
            t0 = time.monotonic()
            for _ in range(WARMUP):
                p["pack"], metrics = p["step_once"](p["pack"])
            _hard_sync(metrics)
            p["warmup_s"] = round(time.monotonic() - t0, 1)
            prepared.append(p)
        except Exception as e:  # keep sweeping; report the failure
            print(json.dumps({"config": name, "error": repr(e)[:300]}),
                  flush=True)
            if p is not None:
                close_member(p)

    spent = {id(p): 0.0 for p in prepared}
    for _ in range(rounds):
        for p in prepared:  # the interleave: one chunk each, round-robin
            if spent[id(p)] >= MAX_SECONDS or "error" in p:
                continue
            t0 = time.monotonic()
            try:
                p["rates"].append(_one_chunk(p))
            except Exception as e:  # the member fails; the GROUP sweeps on
                p["error"] = repr(e)[:300]
            spent[id(p)] += time.monotonic() - t0

    for p in prepared:
        rates = p["rates"]
        row = {
            "config": p["name"],
            "episodes_per_s_per_chip": round(statistics.median(rates), 1)
            if rates else None,
            "spread": [round(min(rates), 1), round(max(rates), 1)]
            if rates else None,
            "chunks": len(rates),
            "warmup_s": p["warmup_s"],
            "backend": jax.default_backend(),
            "mfu": _row_mfu(p["cfg"], rates, p.get("mode", "train")),
        }
        if "error" in p:
            row["error"] = p["error"]
        print(json.dumps(row), flush=True)
        close_member(p)
    prepared.clear()
    gc.collect()  # release each group's device tables before the next


def main() -> int:
    import jax

    from bench import _probe_tpu

    if not _probe_tpu():
        print("bench_sweep: TPU backend unreachable; falling back to CPU",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")

    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    base = dict(batch_size=BATCH, max_length=40, vocab_size=2002,
                compute_dtype="bfloat16", steps_per_call=64)
    tc = lambda **kw: ExperimentConfig(
        token_cache=True, **{**base, "steps_per_call": 512, **kw}
    )
    # GROUPS interleave within one tunnel session: each live config rides
    # next to its token-cache twin, so live-vs-cached is a real A/B.
    groups = [
        [("1: 5w1s cnn", ExperimentConfig(encoder="cnn", n=5, k=1, q=5, **base), False),
         ("1t: 5w1s cnn token_cache", tc(encoder="cnn", n=5, k=1, q=5), False)],
        [("2: 5w5s bilstm", ExperimentConfig(encoder="bilstm", n=5, k=5, q=5, **base), False),
         ("2t: 5w5s bilstm token_cache", tc(encoder="bilstm", n=5, k=5, q=5), False)],
        [("3: 10w5s bilstm", ExperimentConfig(
            encoder="bilstm", train_n=10, n=10, k=5, q=5, **base), False),
         ("3t: 10w5s bilstm token_cache",
          tc(encoder="bilstm", train_n=10, n=10, k=5, q=5), False),
         # 10w1s completes the paper's eval grid (ISSUE 19): the
         # hardest corner — widest class axis, thinnest support.
         ("3o: 10w1s bilstm token_cache",
          tc(encoder="bilstm", train_n=10, n=10, k=1, q=5), False)],
        [("4: 5w5s bert-base frozen", ExperimentConfig(
            encoder="bert", n=5, k=5, q=5, bert_frozen=True,
            **{**base, "batch_size": 2, "steps_per_call": 8}), False),
         ("4b: 5w5s bert-base frozen + feature_cache", ExperimentConfig(
            encoder="bert", n=5, k=5, q=5, bert_frozen=True,
            feature_cache=True, **{**base, "batch_size": 2}), False),
         # BERT-PAIR scores token-level (query, support) sequence pairs
         # through the backbone — N*K forwards per query; the heaviest
         # model in the zoo by construction (the FewRel 2.0 NOTA baseline).
         ("4p: 5w5s BERT-PAIR (bert-base)", ExperimentConfig(
            encoder="bert", model="pair", n=5, k=5, q=5,
            **{**base, "batch_size": 1, "steps_per_call": 2}), False)],
        [("5: 5w5s bilstm na_rate=5 +adv (FewRel2.0)", ExperimentConfig(
            encoder="bilstm", n=5, k=5, q=5, na_rate=5, adv=True,
            **base), True),
         ("5t: 5w5s bilstm na_rate=5 token_cache (NOTA)",
          tc(encoder="bilstm", n=5, k=5, q=5, na_rate=5), False),
         # NOTA fraction = na_rate/(n + na_rate): 5t is the 50% mix; this
         # row adds the light 1/6 mix.
         ("5n: 5w5s bilstm na_rate=1 token_cache (NOTA 1:6)",
          tc(encoder="bilstm", n=5, k=5, q=5, na_rate=1), False)],
        # Reference-shaped embed-optimizer A/B (VERDICT round-2 item 3):
        # full 400k table, dense Adam vs the exact-parity lazy row update
        # vs stateless sgd — interleaved so the lazy win is tunnel-proof.
        # Model-zoo throughput (VERDICT round-2 item 6): every sibling
        # few-shot model on the production token-cache path, interleaved so
        # the ranking is tunnel-proof. Induction rides along as the anchor.
        [(f"7-{m}: 5w5s {m} token_cache",
          tc(encoder="cnn", n=5, k=5, q=5, model=m, steps_per_call=64), False)
         for m in ("induction", "proto", "proto_hatt", "siamese",
                   "gnn", "snail", "metanet")],
        # EVAL-path rows (round-5 VERDICT item 6): the fused eval at the
        # flagship shape on both transports, interleaved with each other.
        # embed_optimizer is train-side machinery; eval scores params as
        # they are, so "shared" keeps the table untouched.
        [("8t: flagship EVAL token_cache (fused lax.map)",
          tc(encoder="bilstm", n=5, k=5, q=5, batch_size=64,
             vocab_size=400002, steps_per_call=256), False, "eval"),
         ("8L: flagship EVAL live tokens (fused)",
          ExperimentConfig(
              encoder="bilstm", n=5, k=5, q=5, vocab_size=400002,
              max_length=40, compute_dtype="bfloat16", batch_size=64,
              steps_per_call=64), False, "eval")],
        # BERT fine-tune MFU row (round-5 VERDICT item 5a): the UNFROZEN
        # backbone — enc_mult=3 in utils/flops.py — so the fine-tune
        # regime finally carries an MFU number next to the frozen path's.
        [("9f: 5w5s bert-base FINE-TUNE (unfrozen)", ExperimentConfig(
            encoder="bert", n=5, k=5, q=5, bert_frozen=False,
            **{**base, "batch_size": 2, "steps_per_call": 8}), False)],
        [("6s: 400k-vocab B64 embed=shared (dense Adam)",
          tc(encoder="bilstm", n=5, k=5, q=5, batch_size=64, vocab_size=400002,
             steps_per_call=256, embed_optimizer="shared"), False),
         ("6l: 400k-vocab B64 embed=lazy (exact-parity sparse)",
          tc(encoder="bilstm", n=5, k=5, q=5, batch_size=64, vocab_size=400002,
             steps_per_call=256, embed_optimizer="lazy"), False),
         ("6g: 400k-vocab B64 embed=sgd",
          tc(encoder="bilstm", n=5, k=5, q=5, batch_size=64, vocab_size=400002,
             steps_per_call=256, embed_optimizer="sgd"), False),
         # LIVE-path lazy (round-3 VERDICT item 3): the per-step
         # sort/dedup body on live token batches vs its dense twin — the
         # CLI accepts this combination, so its cost must be on record
         # (cli warns when it loses; see BASELINE.md round 4).
         ("6Ls: 400k-vocab B64 embed=shared LIVE (no cache)",
          ExperimentConfig(
              encoder="bilstm", n=5, k=5, q=5, vocab_size=400002,
              max_length=40, compute_dtype="bfloat16", batch_size=64,
              steps_per_call=64, embed_optimizer="shared"), False),
         ("6Ll: 400k-vocab B64 embed=lazy LIVE (no cache)",
          ExperimentConfig(
              encoder="bilstm", n=5, k=5, q=5, vocab_size=400002,
              max_length=40, compute_dtype="bfloat16", batch_size=64,
              steps_per_call=64, embed_optimizer="lazy"), False)],
    ]
    only = sys.argv[1:] or None

    def matches(name: str) -> bool:
        # Numeric selectors match the row's id prefix ("6" hits 6s/6l/6g,
        # "1" hits 1/1t but not "3: 10w5s"); non-numeric selectors are
        # substring matches on the description.
        if not only:
            return True
        rid = name.split(":", 1)[0]
        return any(
            rid.startswith(s) if s[0].isdigit() else s in name
            for s in only
        )

    for group in groups:
        group = [m for m in group if matches(m[0])]
        if group:
            run_group(group)
    return 0


if __name__ == "__main__":
    sys.exit(main())
