#!/usr/bin/env python3
"""Profile the headline fused call and rank device ops by total time.

Builds the exact bench.py headline config (token cache, lazy embed Adam,
vocab 400,002, B=64, spc=256 — override with the same BENCH_* env vars),
traces ONE fused call with jax.profiler, then walks the device XPlane and
prints the top ops aggregated by (fused-op) name. This answers "where does
the remaining step time go after lazy-embed removed the dense table term"
with measurements instead of guesses.

Usage:  python tools/profile_headline.py [--top 30]
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--spc", type=int, default=int(os.environ.get("BENCH_SPC", "256")))
    args = ap.parse_args()

    import jax

    import bench

    bench.STEPS_PER_CALL = args.spc

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.native.sampler import make_index_sampler
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_train_step,
        tokenize_dataset,
    )

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=bench.BATCH, max_length=40,
        vocab_size=bench.VOCAB, compute_dtype="bfloat16",
        steps_per_call=args.spc, token_cache=True,
        embed_optimizer=bench.EMBED_OPT,
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=20, instances_per_relation=cfg.k + cfg.q + 5,
        vocab_size=min(cfg.vocab_size - 2, 2000),
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    if cfg.embed_optimizer == "lazy":
        from induction_network_on_fewrel_tpu.train.lazy_embed import (
            augment_token_table,
        )

        table_np, uids = augment_token_table(table_np)
        table_np = {**table_np, "uids": uids}
    table = jax.device_put(table_np)
    sampler = make_index_sampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0
    )
    model = build_model(cfg, glove_init=vocab.vectors)

    b0s, b0q, _ = sampler.sample_fused(1)
    sup = {k: v[b0s[0]] for k, v in table_np.items() if k != "uids"}
    qry = {k: v[b0q[0]] for k, v in table_np.items() if k != "uids"}
    state = init_state(model, cfg, sup, qry)
    multi_step = make_token_cached_multi_train_step(model, cfg)

    def fused_call(state):
        si, qi, lab = sampler.sample_fused(args.spc)
        return multi_step(state, table, si, qi, lab)

    t0 = time.monotonic()
    for _ in range(2):
        state, metrics = fused_call(state)
    _ = float(jax.device_get(metrics["loss"])[-1])
    print(f"warmup(+compile) {time.monotonic() - t0:.1f}s", file=sys.stderr)

    tmpdir = tempfile.mkdtemp(prefix="profile_headline_")
    jax.profiler.start_trace(tmpdir)
    t0 = time.monotonic()
    state, metrics = fused_call(state)
    _ = float(jax.device_get(metrics["loss"])[-1])
    wall = time.monotonic() - t0
    jax.profiler.stop_trace()
    steps = args.spc * bench.BATCH
    print(f"traced call: {wall:.3f}s wall -> {steps / wall:.0f} eps/s", file=sys.stderr)

    files = glob.glob(tmpdir + "/**/*.xplane.pb", recursive=True)
    data = jax.profiler.ProfileData.from_file(files[0])
    for plane in data.planes:
        if "/device:" not in plane.name:
            continue
        print(f"\n=== plane: {plane.name} ===")
        for line in plane.lines:
            per_op: dict[str, tuple[float, int]] = {}
            total = 0
            for e in line.events:
                # Collapse fusion instance suffixes: "fusion.123" -> "fusion"
                # Collapse only dot-number fusion-instance suffixes
                # (possibly stacked, e.g. ".clone.2.1"): a bare [.\d]+
                # also stripped digits that are part of the op name itself
                # and merged genuinely distinct ops (advisor, round 3).
                name = e.name
                while True:
                    stripped = re.sub(r"\.\d+$", "", name)
                    if stripped == name:
                        break
                    name = stripped
                ns, cnt = per_op.get(name, (0.0, 0))
                per_op[name] = (ns + e.duration_ns, cnt + 1)
                total += e.duration_ns
            if not per_op or total == 0:
                continue
            print(f"\n-- line: {line.name}  total {total / 1e6:.1f} ms "
                  f"({total / 1e9 / wall:.1%} of wall)")
            ranked = sorted(per_op.items(), key=lambda kv: -kv[1][0])
            for name, (ns, cnt) in ranked[: args.top]:
                print(f"  {ns / 1e6:9.2f} ms  {cnt:6d}x  {100 * ns / total:5.1f}%  {name}")
    sampler.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
