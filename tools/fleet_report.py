#!/usr/bin/env python3
"""Fleet-wide observability report: cross-process trace stitching + the
journal-correlated incident timeline (ISSUE 17 tentpole).

One run of a fleet is MANY telemetry streams: the router process's
metrics.jsonl (hop records, fleet rollups, journal-op events, autoscaler
ticks), one metrics.jsonl per replica (serve counters, sampled
kind="trace" request waterfalls), and the control plane's write-ahead
log (fleet/journal.py — deliberately timestamp-free, so replay stays
deterministic). This tool folds them back into ONE story:

* **Stitching** — every ``kind="hop"`` record the router emitted names a
  trace_id it handed across the hop; the owning replica's ``kind="trace"``
  record for the same id carries the replica-side segment breakdown.
  Matching them yields the end-to-end waterfall: router route/queue/wire
  around the replica's queue/pack/execute/respond, the replica block
  nested inside the hop's remote window. Hops with no replica-side record
  are UNSTITCHED; replica request traces no hop ever named are ORPHANS —
  both are loud ``--check`` failures (a healthy fleet has neither).
* **Clock discipline** — hop records carry ``offset_ms``, the transport's
  NTP-style per-replica clock-offset estimate (fleet/transport.ClockSync).
  Replica-side absolute timestamps (``t_unix``) are aligned onto the
  router's clock by subtracting it; ``--check`` fails when any estimate
  exceeds ``--skew_bound_ms`` (a fleet whose clocks disagree that much
  cannot be causally ordered and should say so, not render fiction).
* **Journal correlation** — WAL payloads carry no timestamps by
  contract, so the router's ``event="journal_op"`` records (op, seq) are
  where control-plane decisions acquire wall-clock positions. The tool
  replays the WAL read-only (fleet/journal.JournalTailer — it NEVER
  truncates another process's log) and cross-checks every telemetry
  (op, seq) against the replayed record at that seq; a mismatch means
  the streams and the log disagree about history — a loud failure.
* **Incident timeline** — journal ops, scale decisions, promotions, SLO
  burns, health CRITICALs, drift/adapt transitions, replica deaths and
  recoveries from ALL streams, merged on offset-corrected t_unix into
  one causally-ordered ledger: the first artifact to read after a page.

Usage:
    python tools/fleet_report.py FLEET_DIR [--check] [--json]
        [--router DIR] [--replica DIR ...] [--journal DIR]
        [--skew_bound_ms MS] [--waterfalls N]

FLEET_DIR convention (what tools/loadgen.py --fleet_obs_drill lays
down): ``router/`` (the router process's run dir), ``r*/`` (one dir per
replica), ``journal/`` (wal.log + snapshot.json). Explicit flags
override discovery piecewise.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from induction_network_on_fewrel_tpu.fleet.journal import (  # noqa: E402
    SNAPSHOT_NAME,
    WAL_NAME,
    JournalTailer,
)

ROUTER_SEGMENTS = ("route", "queue", "wire", "remote", "respond")
REPLICA_SEGMENTS = ("queue", "pack", "execute", "respond")


# --- stream loading -------------------------------------------------------

def load_stream(run_dir: Path) -> list[dict]:
    """metrics.jsonl -> records, silently skipping unparseable lines
    (tools/obs_report.py --check owns schema enforcement per stream)."""
    path = Path(run_dir) / "metrics.jsonl"
    recs: list[dict] = []
    if not path.exists():
        return recs
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    recs.append(rec)
    return recs


def discover(fleet_dir: Path, router: str | None,
             replicas: list[str], journal: str | None):
    """Resolve (router_dir, {replica_id: dir}, journal_dir) from the
    FLEET_DIR convention, each overridable by an explicit flag."""
    fleet_dir = Path(fleet_dir)
    router_dir = Path(router) if router else (
        fleet_dir / "router" if (fleet_dir / "router").exists()
        else fleet_dir
    )
    if replicas:
        rep_dirs = [Path(r) for r in replicas]
    else:
        rep_dirs = sorted(
            d for d in fleet_dir.iterdir()
            if d.is_dir() and d != router_dir
            and (d / "metrics.jsonl").exists()
        ) if fleet_dir.is_dir() else []
    by_id: dict[str, Path] = {}
    for d in rep_dirs:
        recs = load_stream(d)
        rid = next(
            (r["proc_replica"] for r in recs
             if isinstance(r.get("proc_replica"), str)), d.name,
        )
        by_id[rid] = d
    jdir = Path(journal) if journal else fleet_dir / "journal"
    if not ((jdir / WAL_NAME).exists() or (jdir / SNAPSHOT_NAME).exists()):
        jdir = None
    return router_dir, by_id, jdir


# --- stitching ------------------------------------------------------------

def stitch(router_recs: list[dict],
           replica_recs: dict[str, list[dict]]) -> dict:
    """Match every hop record to its replica-side trace record by
    trace_id. Returns coverage numbers + the stitched list (hop,
    replica_id, replica trace record)."""
    hops = [
        r for r in router_recs
        if r.get("kind") == "hop"
        and isinstance(r.get("trace_id"), str)
    ]
    # trace_id -> (replica id, record); request traces only (a publish
    # control record carries op=... and no per-request total).
    remote: dict[str, tuple[str, dict]] = {}
    for rid, recs in replica_recs.items():
        for r in recs:
            if (r.get("kind") == "trace"
                    and isinstance(r.get("trace_id"), str)
                    and isinstance(r.get("total_ms"), (int, float))
                    and not r.get("op")):
                remote[r["trace_id"]] = (rid, r)
    stitched, unstitched = [], []
    for h in hops:
        hit = remote.pop(h["trace_id"], None)
        if hit is None:
            unstitched.append(h)
        else:
            stitched.append((h, hit[0], hit[1]))
    # What is left in ``remote`` was served traced on a replica but never
    # announced by a hop record: orphaned request traces. (Replica-local
    # sampling with no router in front produces these legitimately — but
    # then there are no hop records either and this tool has nothing to
    # stitch; in a fleet run orphans mean lost telemetry.)
    orphans = sorted(remote)
    n_hops = len(hops)
    return {
        "hop_records": n_hops,
        "stitched": len(stitched),
        "unstitched": len(unstitched),
        "unstitched_frac": round(len(unstitched) / n_hops, 4)
        if n_hops else 0.0,
        "orphan_spans": len(orphans),
        "orphan_trace_ids": orphans[:10],
        "pairs": stitched,
    }


def _bar(offset: float, dur: float, total: float, width: int = 32) -> str:
    scale = width / total if total > 0 else 0.0
    a = int(round(offset * scale))
    b = max(a + 1, int(round((offset + dur) * scale)))
    return " " * a + "#" * min(b - a, width - a)


def waterfall_lines(hop: dict, rid: str, trace: dict) -> list[str]:
    """One stitched trace -> the fleet waterfall: router segments tile
    [0, router_ms]; the replica's segments tile its own total, drawn
    inside the hop's remote window (offset = where remote_ms starts on
    the router timeline — durations need no clock alignment)."""
    total = float(hop.get("router_ms") or 0.0)
    segs = [(s, float(hop.get(f"{s}_ms", 0.0))) for s in ROUTER_SEGMENTS]
    ssum = sum(d for _, d in segs)
    ok = total > 0 and abs(ssum - total) <= 0.05 * total
    lines = [
        f"trace {hop.get('trace_id')} tenant={hop.get('tenant')} "
        f"router->{rid} fleet={total:.3f}ms hop_tax={hop.get('hop_ms')}ms "
        f"(router segments sum {ssum:.3f}ms, "
        f"{'ok' if ok else 'MISMATCH > 5%'})",
    ]
    offset = 0.0
    remote_at = 0.0
    for name, dur in segs:
        if name == "remote":
            remote_at = offset
        lines.append(
            f"  router {name:<8}{dur:9.3f}ms "
            f"|{_bar(offset, dur, total):<32}|"
        )
        offset += dur
    r_total = float(trace.get("total_ms") or 0.0)
    r_segs = [(s, float(trace.get(f"{s}_ms", 0.0)))
              for s in REPLICA_SEGMENTS]
    # The replica block is drawn to the ROUTER's scale, anchored at the
    # remote window — the eye reads the replica's internal breakdown in
    # fleet-time position. (The replica's measured total can exceed the
    # clamped remote window by scheduling jitter; the bars then saturate
    # at the window edge rather than lie about the timeline.)
    r_off = remote_at
    for name, dur in r_segs:
        lines.append(
            f"  {rid:<6} {name:<8}{dur:9.3f}ms "
            f"|{_bar(r_off, dur, total):<32}|"
        )
        r_off += dur
    r_sum = sum(d for _, d in r_segs)
    r_ok = r_total > 0 and abs(r_sum - r_total) <= 0.05 * r_total
    lines.append(
        f"  {rid} total {r_total:.3f}ms (segments sum {r_sum:.3f}ms, "
        f"{'ok' if r_ok else 'MISMATCH > 5%'})"
    )
    return lines


# --- clock skew -----------------------------------------------------------

def clock_offsets(router_recs: list[dict]) -> dict[str, float]:
    """Last offset_ms estimate per replica, off the hop stream."""
    out: dict[str, float] = {}
    for r in router_recs:
        if (r.get("kind") == "hop"
                and isinstance(r.get("replica"), str)
                and isinstance(r.get("offset_ms"), (int, float))):
            out[r["replica"]] = float(r["offset_ms"])
    return out


# --- journal correlation --------------------------------------------------

def journal_correlation(journal_dir: Path | None,
                        router_recs: list[dict]) -> dict | None:
    """Replay the WAL read-only and cross-check every telemetry
    (op, seq) pair against the replayed record at that seq. Seqs folded
    into a snapshot are unverifiable (the ops are gone by design) and
    count separately, not as mismatches."""
    if journal_dir is None:
        return None
    tailer = JournalTailer(journal_dir)
    wal = {int(r["seq"]): str(r.get("op")) for r in tailer.records()
           if isinstance(r.get("seq"), (int, float))}
    snap_path = Path(journal_dir) / SNAPSHOT_NAME
    snap_base = 0
    if snap_path.exists():
        try:
            snap_base = int(
                json.loads(snap_path.read_text()).get("applied", 0)
            )
        except (json.JSONDecodeError, OSError):
            pass
    events = [
        r for r in router_recs
        if r.get("kind") == "fleet" and r.get("event") == "journal_op"
        and isinstance(r.get("seq"), (int, float))
    ]
    mismatches, compacted = [], 0
    for e in events:
        seq = int(e["seq"])
        op = str(e.get("op"))
        if seq in wal:
            if wal[seq] != op:
                mismatches.append(
                    f"seq {seq}: telemetry says {op!r}, WAL says "
                    f"{wal[seq]!r}"
                )
        elif seq < snap_base:
            compacted += 1
        else:
            mismatches.append(
                f"seq {seq} ({op!r}): no WAL record (torn tail? "
                f"wrong journal dir?)"
            )
    return {
        "wal_records": len(wal),
        "snapshot_base": snap_base,
        "journal_op_events": len(events),
        "compacted_unverifiable": compacted,
        "mismatches": mismatches,
        "state": tailer.state.to_dict() if (len(wal) or snap_base)
        else None,
    }


# --- the incident timeline ------------------------------------------------

def _event_label(r: dict) -> str | None:
    """One timeline-worthy record -> its ledger line, None for records
    that are load, not events (ticks, rollups, request traces)."""
    kind = r.get("kind")
    if kind == "fleet":
        ev = r.get("event")
        if ev == "journal_op":
            return f"journal {r.get('op')} seq={int(r.get('seq', -1))}"
        if ev == "fanout_publish":
            return (f"fanout publish -> v{int(r.get('params_version', 0))}"
                    f" across {int(r.get('replicas', 0))} replicas"
                    f" ({r.get('publish_s')}s)")
        if ev == "replica_add":
            return (f"replica {r.get('replica')} joined "
                    f"({int(r.get('replicas', 0))} replicas)")
        if ev == "replica_retire":
            return (f"replica {r.get('replica')} retired "
                    f"({int(r.get('replicas', 0))} replicas)")
        if ev == "replace":
            return f"failover re-placed {int(r.get('moved', 0))} tenants"
        if ev == "journal_compact":
            return (f"journal compacted at seq "
                    f"{int(r.get('snapshot_seq', 0))}")
        return None
    if kind == "scale":
        ev = r.get("event")
        if ev == "scale_out":
            return (f"autoscaler scale_out {r.get('replica')} "
                    f"(occupancy={r.get('occupancy')} "
                    f"shed_delta={r.get('shed_delta')})")
        if ev == "drain_in":
            return (f"autoscaler drain_in {r.get('replica')} "
                    f"moved={int(r.get('moved', 0))}")
        if ev == "promotion":
            return (f"standby PROMOTED in {r.get('promote_s')}s "
                    f"(lease epoch {int(r.get('lease_epoch', 0))})")
        return None
    if kind == "fault":
        a = r.get("action")
        if a == "replica_dead":
            return (f"replica {r.get('replica')} DEAD "
                    f"({r.get('reason')}; {int(r.get('tenants', 0))} "
                    f"tenants affected)")
        if a == "replica_recover":
            return f"replica {r.get('replica')} recovered ({r.get('reason')})"
        if a == "publish_rollback":
            return f"publish ROLLED BACK: {r.get('reason')}"
        if a == "recovered":
            return (f"cold-start recovery: {int(r.get('tenants', 0))} "
                    f"tenants, {int(r.get('reregistered', 0))} "
                    f"re-registered")
        if a == "breaker":
            return (f"breaker {r.get('tenant')}: {r.get('from')} -> "
                    f"{r.get('to')}")
        if a == "scale_stuck":
            return f"scale {r.get('direction')} STUCK: {r.get('reason')}"
        return None
    if kind == "health":
        ev = str(r.get("event", ""))
        if ev.startswith("slo_"):
            return (f"SLO {ev} tenant={r.get('tenant')} "
                    f"burn_fast={r.get('burn_fast')}")
        if r.get("severity") == "critical":
            return f"CRITICAL {ev}: {r.get('message')}"
        return None
    if kind == "adapt":
        return (f"adapt {r.get('action')} tenant={r.get('tenant')} "
                f"state={r.get('state')}")
    return None


def build_timeline(router_recs: list[dict],
                   replica_recs: dict[str, list[dict]],
                   offsets: dict[str, float]) -> dict:
    """Merge event-worthy records from every stream onto the ROUTER's
    clock: replica t_unix minus that replica's offset estimate (offset =
    replica − router by the ClockSync convention). Records without
    t_unix (identity stamping off) cannot be placed across processes and
    are counted, not guessed at."""
    events: list[tuple[float, str, str]] = []
    unplaced = 0

    def fold(recs: list[dict], src: str, shift_ms: float) -> None:
        nonlocal unplaced
        for r in recs:
            label = _event_label(r)
            if label is None:
                continue
            t = r.get("t_unix")
            if not isinstance(t, (int, float)):
                unplaced += 1
                continue
            events.append((float(t) - shift_ms / 1e3, src, label))

    fold(router_recs, "router", 0.0)
    for rid, recs in replica_recs.items():
        fold(recs, rid, offsets.get(rid, 0.0))
    events.sort(key=lambda e: e[0])
    t0 = events[0][0] if events else 0.0
    return {
        "events": len(events),
        "unplaced_events": unplaced,
        "lines": [
            f"+{t - t0:9.3f}s  {src:<8} {label}"
            for t, src, label in events
        ],
        "raw": [
            {"t": round(t - t0, 6), "src": src, "event": label}
            for t, src, label in events
        ],
    }


# --- report ---------------------------------------------------------------

def build_report(fleet_dir: Path, router_dir: Path,
                 replica_dirs: dict[str, Path],
                 journal_dir: Path | None, skew_bound_ms: float,
                 n_waterfalls: int) -> dict:
    router_recs = load_stream(router_dir)
    replica_recs = {rid: load_stream(d)
                    for rid, d in sorted(replica_dirs.items())}
    st = stitch(router_recs, replica_recs)
    offsets = clock_offsets(router_recs)
    jc = journal_correlation(journal_dir, router_recs)
    tl = build_timeline(router_recs, replica_recs, offsets)

    # The slowest stitched traces get waterfalls (the ones worth reading).
    pairs = sorted(
        st.pop("pairs"),
        key=lambda p: -float(p[0].get("router_ms", 0.0)),
    )[:max(n_waterfalls, 0)]
    waterfalls = [waterfall_lines(h, rid, t) for h, rid, t in pairs]
    tiling_ok = sum(
        1 for h, _, _ in pairs
        if float(h.get("router_ms", 0.0)) > 0 and abs(
            sum(float(h.get(f"{s}_ms", 0.0)) for s in ROUTER_SEGMENTS)
            - float(h["router_ms"])
        ) <= 0.05 * float(h["router_ms"])
    )

    failures: list[str] = []
    if st["hop_records"] == 0:
        failures.append("no hop records — is this a fleet run dir with "
                        "trace sampling on?")
    if st["unstitched"]:
        failures.append(
            f"{st['unstitched']} hop(s) unstitched "
            f"(frac {st['unstitched_frac']}) — replica-side trace "
            f"records missing"
        )
    if st["orphan_spans"]:
        failures.append(
            f"{st['orphan_spans']} orphan replica trace(s) no hop ever "
            f"named: {st['orphan_trace_ids']}"
        )
    worst_skew = max((abs(v) for v in offsets.values()), default=0.0)
    if worst_skew > skew_bound_ms:
        failures.append(
            f"clock skew {worst_skew}ms exceeds bound {skew_bound_ms}ms "
            f"— cross-process ordering untrustworthy"
        )
    if jc is not None and jc["mismatches"]:
        failures.extend(f"journal: {m}" for m in jc["mismatches"])
    if pairs and tiling_ok < len(pairs):
        failures.append(
            f"{len(pairs) - tiling_ok} rendered waterfall(s) with "
            f"router segments summing outside 5% of fleet latency"
        )

    return {
        "fleet_dir": str(fleet_dir),
        "router_dir": str(router_dir),
        "replicas": {rid: str(d) for rid, d in replica_dirs.items()},
        "journal_dir": str(journal_dir) if journal_dir else None,
        "stitching": st,
        "clock_offset_ms": offsets,
        "worst_skew_ms": worst_skew,
        "skew_bound_ms": skew_bound_ms,
        "journal": jc,
        "timeline": tl,
        "waterfalls": waterfalls,
        "failures": failures,
    }


def render(report: dict) -> str:
    lines = [f"== fleet report: {report['fleet_dir']} =="]
    lines.append(
        f"router: {report['router_dir']}  replicas: "
        f"{', '.join(sorted(report['replicas'])) or '(none)'}  journal: "
        f"{report['journal_dir'] or '(none)'}"
    )
    st = report["stitching"]
    lines.append("-- stitching --")
    lines.append(
        f"  hops={st['hop_records']} stitched={st['stitched']} "
        f"unstitched={st['unstitched']} (frac {st['unstitched_frac']}) "
        f"orphans={st['orphan_spans']}"
    )
    if report["clock_offset_ms"]:
        lines.append("-- clock --")
        for rid in sorted(report["clock_offset_ms"]):
            lines.append(
                f"  {rid}: offset {report['clock_offset_ms'][rid]}ms "
                f"(bound {report['skew_bound_ms']}ms)"
            )
    jc = report["journal"]
    if jc:
        lines.append("-- journal --")
        lines.append(
            f"  wal_records={jc['wal_records']} "
            f"snapshot_base={jc['snapshot_base']} "
            f"journal_op_events={jc['journal_op_events']} "
            f"mismatches={len(jc['mismatches'])}"
        )
    for wf in report["waterfalls"]:
        lines.append("-- waterfall --")
        lines.extend(f"  {x}" for x in wf)
    tl = report["timeline"]
    lines.append(
        f"-- timeline ({tl['events']} events, "
        f"{tl['unplaced_events']} unplaced) --"
    )
    lines.extend(f"  {x}" for x in tl["lines"])
    if report["failures"]:
        lines.append("-- FAILURES --")
        lines.extend(f"  ! {f}" for f in report["failures"])
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stitch a fleet's telemetry streams + WAL into one "
                    "cross-process report"
    )
    ap.add_argument("fleet_dir", help="fleet run dir (router/ r*/ journal/)")
    ap.add_argument("--router", help="router run dir override")
    ap.add_argument("--replica", action="append", default=[],
                    help="replica run dir (repeatable) override")
    ap.add_argument("--journal", help="journal dir override")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any stitching/skew/journal failure")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--skew_bound_ms", type=float, default=250.0,
                    help="max tolerated |clock offset| estimate")
    ap.add_argument("--waterfalls", type=int, default=3,
                    help="stitched waterfalls to render (slowest first)")
    args = ap.parse_args(argv)

    fleet_dir = Path(args.fleet_dir)
    router_dir, replica_dirs, journal_dir = discover(
        fleet_dir, args.router, args.replica, args.journal
    )
    if not (router_dir / "metrics.jsonl").exists():
        print(f"no metrics.jsonl under {router_dir}", file=sys.stderr)
        return 2
    report = build_report(
        fleet_dir, router_dir, replica_dirs, journal_dir,
        args.skew_bound_ms, args.waterfalls,
    )
    if args.as_json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(render(report))
    if args.check:
        for f in report["failures"]:
            print(f"fleet check: {f}", file=sys.stderr)
        print(f"{'FAIL' if report['failures'] else 'OK'}: "
              f"{report['stitching']['stitched']} stitched, "
              f"{report['timeline']['events']} timeline events, "
              f"{len(report['failures'])} failures")
        return 1 if report["failures"] else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
