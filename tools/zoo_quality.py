#!/usr/bin/env python3
"""Model-zoo quality sweep: best synthetic-val accuracy per few-shot model.

VERDICT round-2 item 6: every zoo model needs a quality number next to its
correctness test. Runs the production CLI (train.py) once per model at the
flagship quality recipe (5w5s, token cache, damped LR staircase — the
round-2 BASELINE.md recipe that avoids the synthetic overfit walk) and
emits one JSON line per model: {model, final_val_accuracy, train_eps_s}.

Synthetic corpus only (no FewRel on disk) — the numbers bound the TASK,
not FewRel; their value is relative: a zoo model far below its siblings
has a head/geometry bug, not a data problem.

Usage: python tools/zoo_quality.py [model ...]  (default: all)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ZOO = ("induction", "proto", "proto_hatt", "siamese", "gnn", "snail", "metanet")

COMMON = [
    "--encoder", "cnn", "--N", "5", "--K", "5", "--Q", "5",
    "--batch_size", "8", "--max_length", "40", "--vocab_size", "2002",
    "--token_cache", "--steps_per_call", "64", "--bf16",
    "--loss", "ce",  # uniform across the zoo: several heads (metric-based
    # logits) sit far from the MSE-sigmoid calibration the induction paper
    # assumes; CE ranks them on equal footing
    "--lr", "1e-3", "--lr_step_size", "500",  # round-2 damped recipe
    "--train_iter", "4000", "--val_step", "500", "--val_iter", "200",
    "--divergence_guard", "stop",
]


def run_model(model: str, extra=()) -> dict:
    ckpt = tempfile.mkdtemp(prefix=f"zoo_{model}_")
    cmd = [sys.executable, os.path.join(REPO, "train.py"), "--model", model,
           *COMMON, *extra, "--save_ckpt", ckpt]
    # APPEND to PYTHONPATH: this image's axon TPU plugin is delivered via
    # PYTHONPATH (/root/.axon_site); replacing the variable silently drops
    # the TPU backend from child processes.
    pp = os.pathsep.join(filter(None, [REPO, os.environ.get("PYTHONPATH")]))
    row = {"model": model}
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=3600, cwd=REPO,
            env={**os.environ, "PYTHONPATH": pp},
        )
    except subprocess.TimeoutExpired:
        # One wedged tunnel run must not abort the whole zoo sweep.
        row["error"] = "timeout after 3600s"
        return row
    if proc.returncode != 0:
        row["error"] = proc.stderr[-400:]
        return row
    try:
        last = json.loads(proc.stdout.strip().splitlines()[-1])
        row.update(last)
    except Exception:
        row["error"] = "no final JSON: " + proc.stdout[-200:]
    # steady-state train eps/s from the metrics log (median of the last
    # half of train windows — skips compile and early-ckpt noise)
    try:
        rates = []
        with open(os.path.join(ckpt, "metrics.jsonl")) as fh:
            for line in fh:
                rec = json.loads(line)
                if rec.get("kind") == "train" and "episodes_per_s" in rec:
                    rates.append(rec["episodes_per_s"])
        tail = sorted(rates[len(rates) // 2:])
        if tail:
            row["train_eps_s_median"] = round(tail[len(tail) // 2], 1)
    except OSError:
        pass
    return row


def main() -> int:
    picks = sys.argv[1:] or ZOO
    for model in picks:
        print(json.dumps(run_model(model)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
