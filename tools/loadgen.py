#!/usr/bin/env python3
"""Serving load generator: multi-tenant closed/open-loop traffic against the
inference engine, with a continuous-vs-microbatch scheduler A/B and a
hot-swap-under-load drill, stamped into a ``SERVE_r*.json`` artifact.

The acceptance harness for serving/ (ISSUE 1, fleet-scaled by ISSUE 7). On
CPU against a synthetic-data checkpoint it must show:

* **Parity** — registry-based scoring matches the direct episodic forward
  pass to numerical tolerance, PER TENANT, before any load is generated.
* **Zero recompiles** — after warmup, steady-state traffic of every batch
  size and every tenant compiles nothing (the acceptance gate).
* **Scheduler A/B** (``--scheduler ab``) — the same offered load runs once
  under the continuous cross-bucket scheduler and once under the
  per-bucket micro-batcher; the artifact records sustained qps and
  p50/p99 per arm, per tenant.
* **Hot-swap drill** (``--swap_drill``) — a dedicated open-loop phase in
  which a new params version publishes into the live engine mid-load (the
  train->serve recipe); the drill asserts ZERO dropped queries and ZERO
  recompiles across the swap. Separate phase so the publish's device
  contention never skews the scheduler A/B numbers.
* **Request tracing** (``--trace_sample``, ISSUE 9) — head-sampled
  requests emit kind="trace" segment records (queue/pack/execute/respond)
  to ``--run_dir``; the artifact stamps segment-breakdown medians +
  exemplar trace_ids per arm, so a scheduler A/B attributes WHICH stage
  moved. Render waterfalls with ``tools/obs_report.py RUN_DIR``.
* **Burn-rate drill** (``--burn_drill``, ISSUE 9) — a dedicated overload
  phase: open-loop traffic at several times the offered rate drives
  latency through the SLO threshold; the drill asserts the fast window
  trips a once-latched CRITICAL and that the auto-captured diagnostics
  (flight dump + profiler trace or host-span snapshot) are on disk.
* **Drift drill** (``--drift_drill``, ISSUE 10) — a dedicated
  model-quality phase on its own engine: calibrate an open-set NOTA
  floor from live verdict gaps (a deterministic split between the
  in-domain clean pool and a constant out-of-vocabulary probe), arm the
  prediction-drift detector (obs/drift.py), baseline in-domain traffic,
  then inject an OOV traffic shift. The drill asserts the NOTA-rate
  shift trips a once-latched CRITICAL with diagnostics captured, that
  continued shifted traffic emits nothing new (once-latch), and that a
  hot-swap publish re-arms the baseline so post-publish in-domain
  traffic is judged clean against the NEW normal.

* closed loop: C workers, each submitting synchronously — throughput is
  latency-bound, the classic "how fast can N clients go" number.
* open loop: Poisson arrivals at a fixed offered rate — latency under a
  load the clients do NOT adapt to, where queueing/backpressure shows up.

Usage:
    python tools/loadgen.py [--ckpt DIR] [--mode closed|open|both]
        [--scheduler continuous|microbatch|ab] [--tenants 2]
        [--swap_drill] [--artifact SERVE_r01.json]
        [--concurrency 4] [--rate 200] [--duration 5] [--N 5] [--K 5]
        [--run_dir OUT] [--trace_sample 0.1]
        [--burn_drill] [--slo_latency_ms 50] [--slo_fast_s 3]

No --ckpt: a synthetic-data checkpoint is created in a temp dir (fresh-init
weights saved + restored through the real CheckpointManager read path).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--ckpt", default=None,
                   help="checkpoint dir to serve (default: build a "
                        "synthetic-data checkpoint in a temp dir)")
    p.add_argument("--mode", default="both", choices=["closed", "open", "both"])
    p.add_argument("--scheduler", default="ab",
                   choices=["continuous", "microbatch", "ab"],
                   help="which scheduler to drive; 'ab' runs the same load "
                        "under both and records the comparison")
    p.add_argument("--tenants", type=int, default=2,
                   help="registered tenants, each with its own synthetic "
                        "relation set; traffic round-robins across them")
    p.add_argument("--swap_drill", action="store_true",
                   help="publish a new params version mid-load and assert "
                        "zero dropped queries + zero recompiles")
    p.add_argument("--artifact", default=None, metavar="PATH",
                   help="write the SERVE_r*.json artifact here")
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop client threads")
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop offered rate (queries/s, all tenants)")
    p.add_argument("--duration", type=float, default=5.0,
                   help="seconds per load phase")
    p.add_argument("--N", type=int, default=5, help="classes per tenant")
    p.add_argument("--K", type=int, default=5, help="shots per class")
    p.add_argument("--na_rate", type=int, default=0,
                   help="train-config NOTA rate for the synthetic checkpoint "
                        "(>0 builds the no-relation head)")
    p.add_argument("--buckets", default="1,2,4,8,16")
    p.add_argument("--queue_depth", type=int, default=64)
    p.add_argument("--tenant_share", type=float, default=0.5)
    p.add_argument("--deadline_ms", type=float, default=1000.0)
    p.add_argument("--batch_window_ms", type=float, default=2.0)
    p.add_argument("--serving_dp", type=int, default=None,
                   help="dp-shard query scoring over this many devices")
    p.add_argument("--device", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--run_dir", default=None,
                   help="telemetry dir: metrics.jsonl (kind='serve'/'trace'"
                        "), flight dumps + SLO captures land here; render "
                        "with tools/obs_report.py")
    p.add_argument("--trace_sample", type=float, default=0.1,
                   help="request-trace head-sampling rate (0 = off); "
                        "sampled segment records reach --run_dir and the "
                        "artifact's per-arm trace summary")
    p.add_argument("--slo_latency_ms", type=float, default=None,
                   help="per-request latency objective (arms the SLO "
                        "burn-rate engine; the burn drill derives one "
                        "from measured p50 when unset)")
    p.add_argument("--slo_availability", type=float, default=0.99,
                   help="SLO good-fraction target")
    p.add_argument("--slo_fast_s", type=float, default=3.0,
                   help="fast burn window seconds (drill-scaled stand-in "
                        "for the production 5m window)")
    p.add_argument("--slo_slow_s", type=float, default=30.0,
                   help="slow burn window seconds (1h-equivalent)")
    p.add_argument("--burn_drill", action="store_true",
                   help="overload phase per arm: drive latency through "
                        "the SLO threshold, assert the fast window trips "
                        "a once-latched CRITICAL + diagnostics captured "
                        "(requires --run_dir for the artifacts)")
    p.add_argument("--drift_drill", action="store_true",
                   help="model-quality phase on its own engine: calibrate "
                        "a NOTA floor, baseline in-domain traffic, inject "
                        "an out-of-vocabulary shift, assert the drift "
                        "detector trips a once-latched CRITICAL with "
                        "captures and that a publish re-arms the baseline "
                        "(requires --run_dir)")
    p.add_argument("--chaos_drill", action="store_true",
                   help="fault-domain drill on its own engine (ISSUE 12): "
                        "inject execute faults (circuit breaker must trip "
                        "once-latched, the tenant must recover through a "
                        "half-open probe), a poisoned publish (the "
                        "transactional rollback must hold: registry "
                        "generation unchanged, zero dropped in-flight "
                        "requests, zero recompiles), and a corrupted ring "
                        "slot (resume must quarantine it and continue "
                        "bitwise from the newest intact slot); drift/SLO "
                        "latches must re-arm after recovery (requires "
                        "--run_dir)")
    p.add_argument("--chaos_artifact", default=None, metavar="PATH",
                   help="write the CHAOS_r*.json drill artifact here")
    p.add_argument("--adapt_drill", action="store_true",
                   help="self-healing adaptation drill (ISSUE 14), "
                        "standalone mode on its own miniature world: "
                        "SUCCESS arm — inject an OOV domain shift, the "
                        "drift CRITICAL triggers the controller, a "
                        "mixture-ramp fine-tune passes the scenario-"
                        "harness canary and fan-out-publishes into a "
                        "3-replica fleet (0 dropped, 0 recompiles, "
                        "params_version uniform), the tenant's NOTA "
                        "rate returns to band and the detector re-arms; "
                        "FAILURE arm — chaos adapt.canary_fail discards "
                        "every candidate (zero publishes), backoff is "
                        "honored, and the retry budget exhausts into a "
                        "latched adapt_exhausted CRITICAL + quarantine "
                        "(requires --run_dir)")
    p.add_argument("--adapt_artifact", default=None, metavar="PATH",
                   help="write the ADAPT_r*.json drill artifact here")
    p.add_argument("--fleet", type=int, default=0, metavar="R",
                   help="fleet soak mode (ISSUE 13): build R in-process "
                        "engine replicas behind the fleet router, spread "
                        "--tenants tenants across them by rendezvous "
                        "placement, drive mixed closed-loop traffic, "
                        "fan-out one all-or-nothing publish mid-load, "
                        "measure placement churn on a replica add, and "
                        "run the fleet.replica_kill failover drill "
                        "(degraded NOTA -> re-place -> recover). "
                        "Standalone mode: the scheduler arms are skipped. "
                        "0 = off")
    p.add_argument("--fleet_artifact", default=None, metavar="PATH",
                   help="write the FLEET_r*.json soak artifact here")
    p.add_argument("--recovery_drill", action="store_true",
                   help="durable-control-plane drill (ISSUE 15), "
                        "standalone mode on its own miniature journaled "
                        "fleet: kill the router mid-life (one replica "
                        "host lost with it) -> recover(journal) rebuilds "
                        "the directory BITWISE with identical placement, "
                        "zero tenants lost, the fresh replica "
                        "re-registered + caught up to the journaled "
                        "params_version; kill a replica -> the "
                        "supervisor restarts it (backoff honored on an "
                        "injected clock) with automatic catch-up to the "
                        "uniform generation, zero drops, zero steady "
                        "recompiles; tear the journal tail -> replay "
                        "truncates at the bad record and recovers "
                        "everything before it")
    p.add_argument("--recovery_artifact", default=None, metavar="PATH",
                   help="write the RECOVERY_r*.json drill artifact here")
    p.add_argument("--elastic_drill", action="store_true",
                   help="elasticity drill (ISSUE 16), standalone mode on "
                        "its own miniature journaled fleet: ramp -> the "
                        "autoscaler scales out (spawn, journaled catch-up, "
                        "pre-warm BEFORE traffic — zero recompiles through "
                        "the scale event); trough -> drain-in (drain, "
                        "wait-for-inflight, replace, retire — nothing "
                        "dropped); second ramp -> router kill-9 "
                        "mid-decision -> the WAL-tailing hot standby "
                        "promotes (lease fences the zombie primary, final "
                        "catch-up replay, directory BITWISE, tenants "
                        "served degraded-NOTA during the window, never "
                        "dropped)")
    p.add_argument("--elastic_artifact", default=None, metavar="PATH",
                   help="write the ELASTIC_r*.json drill artifact here")
    p.add_argument("--fleet_obs_drill", action="store_true",
                   help="fleet observability drill (ISSUE 17), standalone "
                        "mode on its own miniature fleet: 3 replicas with "
                        "per-process telemetry streams laid out as the "
                        "tools/fleet_report.py run-dir convention "
                        "(router/, r*/, journal/), open-loop load through "
                        "one scale-out + one replica kill + one fan-out "
                        "publish mid-run; asserts every sampled hop "
                        "stitches to its replica-side trace, zero orphan "
                        "spans, the incidents land in the timeline in "
                        "fire order, and fleet_report --check is green "
                        "(requires --run_dir: the fleet layout lands "
                        "there)")
    p.add_argument("--obsfleet_artifact", default=None, metavar="PATH",
                   help="write the OBSFLEET_r*.json drill artifact here")
    p.add_argument("--quant_ab", action="store_true",
                   help="standalone quantized-serving A/B drill (ISSUE "
                        "18): three arms — f32 / bf16 / int8 resident "
                        "class vectors — under the same open-loop "
                        "arrivals, parity-probing every quantized batch "
                        "against f32; stamps qps, tails, verdict "
                        "agreement, margin drift, resident bytes per "
                        "tenant and the projected tenants-per-chip "
                        "density into QUANT_r*.json")
    p.add_argument("--quant_artifact", default=None, metavar="PATH",
                   help="write the QUANT_r*.json drill artifact here")
    p.add_argument("--geom_ab", action="store_true",
                   help="standalone mixed-geometry A/B drill (ISSUE 19): "
                        "two arms — N-tier bucketed vs exact-N resident "
                        "class stacks — serving the same mixed-N tenant "
                        "set (N spanning 3..40) under the same open-loop "
                        "arrivals, with a mid-drill tier-crossing "
                        "re-registration and a resident-dtype flip; "
                        "stamps per-arm program count, qps, parity and "
                        "steady recompiles plus the (N, K) scenario grid "
                        "legs into GEOM_r*.json")
    p.add_argument("--geom_artifact", default=None, metavar="PATH",
                   help="write the GEOM_r*.json drill artifact here")
    p.add_argument("--slo_profile", action="store_true",
                   help="also attempt a jax.profiler trace in the SLO "
                        "auto-capture (default off: on this image a "
                        "profiler session concurrent with the threaded "
                        "serving worker corrupts the heap and segfaults "
                        "at interpreter exit — RUNBOOK §14; the host-span "
                        "snapshot + flight dump are the guaranteed "
                        "artifacts, chip sessions can flip this on)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.burn_drill and not args.run_dir:
        p.error("--burn_drill needs --run_dir (captures land there)")
    if args.drift_drill and not args.run_dir:
        p.error("--drift_drill needs --run_dir (captures land there)")
    if args.chaos_drill and not args.run_dir:
        p.error("--chaos_drill needs --run_dir (captures land there)")
    if args.adapt_drill and not args.run_dir:
        p.error("--adapt_drill needs --run_dir (captures land there)")
    if args.fleet_obs_drill and not args.run_dir:
        p.error("--fleet_obs_drill needs --run_dir (the fleet's "
                "multi-stream layout lands there)")
    return args


def make_synthetic_checkpoint(args, tmpdir: str, train_iters: int = 0) -> str:
    """Fresh-init induction weights saved through the real CheckpointManager
    (so the engine exercises the genuine restore path).

    ``train_iters > 0`` (the --quant_ab path) first trains briefly on a
    disjoint-seed synthetic corpus so the served verdicts carry REAL
    margins: an untrained model scores near-ties everywhere, and argmax
    over near-ties flips under ANY numeric noise — a parity floor
    measured on it gauges the tie-breaking, not the quantization."""
    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import make_synthetic_glove
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = ExperimentConfig(
        device=args.device, n=args.N, train_n=args.N, k=args.K,
        na_rate=args.na_rate, vocab_size=2002, seed=args.seed,
        val_step=0,
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch

    model = build_model(cfg, glove_init=vocab.vectors)
    state = init_state(model, cfg,
                       zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
                       zero_batch(cfg.max_length, (1, cfg.total_q)),
                       rng=jax.random.key(cfg.seed))
    if train_iters > 0:
        from induction_network_on_fewrel_tpu.data import (
            GloveTokenizer,
            make_synthetic_fewrel,
        )
        from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
        from induction_network_on_fewrel_tpu.train import FewShotTrainer
        from induction_network_on_fewrel_tpu.utils.metrics import (
            MetricsLogger,
        )

        train_ds = make_synthetic_fewrel(
            num_relations=max(args.N, 5) * 2,
            instances_per_relation=args.K + 10,
            vocab_size=2000, seed=args.seed + 9999,
        )
        tok = GloveTokenizer(vocab, max_length=cfg.max_length)
        trainer = FewShotTrainer(
            model, cfg,
            EpisodeSampler(train_ds, tok, n=cfg.n, k=cfg.k, q=cfg.q,
                           batch_size=cfg.batch_size,
                           na_rate=cfg.na_rate, seed=args.seed + 1),
            logger=MetricsLogger(quiet=True),
        )
        state = trainer.train(num_iters=train_iters, state=state)
    ckpt = os.path.join(tmpdir, "ckpt")
    mngr = CheckpointManager(ckpt, cfg, stage="off")
    try:
        mngr.save(0, state, val_accuracy=0.0)
        mngr.wait()
    finally:
        mngr.close()
    return ckpt


def build_engine(args, ckpt: str, scheduler: str, logger=None, slo=None,
                 drift=None, breaker=None, resident_dtype=None,
                 quant_probe_every=None, geometry_tiers=None):
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine

    return InferenceEngine.from_checkpoint(
        ckpt, device=args.device, k=args.K,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        max_queue_depth=args.queue_depth,
        batch_window_s=args.batch_window_ms / 1e3,
        default_deadline_s=args.deadline_ms / 1e3,
        scheduler=scheduler, tenant_share=args.tenant_share,
        dp=args.serving_dp,
        logger=logger, slo=slo, drift=drift, breaker=breaker,
        trace_sample=args.trace_sample,
        resident_dtype=resident_dtype,
        quant_probe_every=quant_probe_every,
        geometry_tiers=geometry_tiers,
    )


def build_slo(args, logger=None, recorder=None, capture=None):
    """One SLOEngine per arm (fresh burn windows — the A/B arms must not
    share budget history); the DiagnosticsCapture is SHARED across arms
    (its per-capture counter keeps every arm's snapshots distinct on
    disk). None when nothing asked for it."""
    if args.slo_latency_ms is None and not args.burn_drill:
        return None
    from induction_network_on_fewrel_tpu.obs import SLOEngine, SLOObjective

    return SLOEngine(
        SLOObjective(availability=args.slo_availability,
                     latency_ms=args.slo_latency_ms),
        fast_window_s=args.slo_fast_s, slow_window_s=args.slo_slow_s,
        logger=logger, recorder=recorder, capture=capture,
    )


def register_tenants(engine, args) -> dict:
    """``--tenants`` synthetic relation sets, one per tenant (distinct
    seeds -> distinct supports, the multi-tenant workload); returns
    {tenant: dataset}."""
    from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel

    tenants = {}
    for t in range(max(args.tenants, 1)):
        name = f"tenant{t}"
        ds = make_synthetic_fewrel(
            num_relations=args.N, instances_per_relation=args.K + 10,
            vocab_size=2000, seed=args.seed + 101 * t,
        )
        engine.register_dataset(ds, tenant=name)
        tenants[name] = ds
    return tenants


def check_registry_parity(engine, ds, tenant: str = "default") -> float:
    """Registry scoring vs the direct episodic forward pass: one episode of
    the registered supports + held-out queries through BOTH paths."""
    import numpy as np

    from induction_network_on_fewrel_tpu.serving.buckets import QUERY_DTYPES

    k = engine.registry.k
    snap = engine.registry.snapshot(tenant)
    names = list(snap.names)
    tok = engine.tokenizer

    def stack(insts, lead):
        toks = [tok(i) for i in insts]
        return {
            key: np.stack([getattr(t, key) for t in toks])
            .astype(dt).reshape((1,) + lead + (-1,))
            for key, dt in QUERY_DTYPES.items()
        }

    # One query per class, capped at the largest query bucket (a
    # wide-N tenant — the geom drill goes to 40 classes — still parity-
    # checks on its full support stack; only the query rows are capped).
    qcap = min(len(names), max(engine.batcher.buckets))
    sup = stack(
        [i for r in names for i in (list(ds.instances[r]) * k)[:k]],
        (len(names), k),
    )
    qry = stack([ds.instances[r][-1] for r in names[:qcap]], (qcap,))
    direct = np.asarray(
        engine.model.apply(snap.params, sup, qry)
    )[0]
    # The served side pads to a real shape bucket (exactly what the batcher
    # does), so this check reuses warmed programs instead of compiling a
    # one-off shape that would trip the steady-recompile counter.
    from induction_network_on_fewrel_tpu.serving.buckets import (
        pad_rows,
        select_bucket,
    )

    bucket = select_bucket(qcap, engine.batcher.buckets)
    # snap.scale is the per-tenant int8 dequant scale (None for f32/bf16
    # residents) — a quantized tenant's parity is checked on its REAL
    # serving path, quantization error and all; the caller picks the
    # tolerance per resident dtype.
    served = engine.programs.run(
        snap.params, snap.matrix,
        {key: pad_rows(qry[key][0], bucket) for key in qry},
        scale=snap.scale,
    )[:qcap]
    # N-tier residency (ISSUE 19): the served row carries n_tier class
    # columns (only the first n real) with the NOTA logit appended LAST;
    # the direct episodic forward is exact-N. Compare the real class
    # columns plus — when the head exists — the NOTA column, i.e.
    # exactly the columns verdicts read.
    n = len(names)
    if direct.shape[-1] == n:          # no NOTA head
        served = served[:, :n]
    else:                              # [real classes..., NOTA]
        served = np.concatenate([served[:, :n], served[:, -1:]], axis=1)
    return float(np.max(np.abs(direct - served)))


def _pools(tenants: dict, k: int) -> dict:
    """Held-out (post-support) query instances per tenant."""
    return {
        t: [inst for r in ds.rel_names for inst in ds.instances[r][k:]]
        for t, ds in tenants.items()
    }


def run_closed(engine, pools, concurrency, duration, rng):
    """C synchronous workers round-robining tenants; returns per-tenant
    latency lists + error count + wall + per-tenant retry counts.

    Backpressure discipline (ISSUE 12 satellite): a ``Saturated`` (or
    typed ``ExecuteError``) carries ``retry_after_s`` — the worker
    HONORS it with deterministic jittered backoff (the worker's own
    seeded rng: hint x U[0.75, 1.25), capped at the remaining phase
    time) instead of hot-spinning resubmits into a queue that just shed
    it. Retries are counted per tenant and stamped into the artifact."""
    names = list(pools)
    lat = {t: [] for t in names}
    retries = {t: 0 for t in names}
    errs = [0]
    stop = time.monotonic() + duration
    lock = threading.Lock()

    def worker(seed):
        import numpy as np

        r = np.random.default_rng(seed)
        mine = {t: [] for t in names}
        my_retries = {t: 0 for t in names}
        i = seed
        while time.monotonic() < stop:
            tenant = names[i % len(names)]
            i += 1
            pool = pools[tenant]
            inst = pool[int(r.integers(len(pool)))]
            t0 = time.monotonic()
            try:
                engine.classify(inst, tenant=tenant)
                mine[tenant].append(time.monotonic() - t0)
            except Exception as e:  # noqa: BLE001 — counted, load continues
                with lock:
                    errs[0] += 1
                hint = getattr(e, "retry_after_s", None)
                if hint is not None:
                    my_retries[tenant] += 1
                    delay = float(hint) * (0.75 + 0.5 * float(r.random()))
                    time.sleep(
                        max(0.0, min(delay, stop - time.monotonic()))
                    )
        with lock:
            for t in names:
                lat[t].extend(mine[t])
                retries[t] += my_retries[t]

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(concurrency)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    return lat, errs[0], wall, retries


def run_open(engine, pools, rate, duration, rng, swap_at=None, swap_fn=None,
             deadline_s=None):
    """Poisson arrivals at ``rate``/s round-robining tenants; non-adaptive
    (futures collected at the end) — saturation surfaces as Saturated
    rejections + p99 growth. ``swap_fn`` fires once after ``swap_at``
    seconds (the hot-swap-under-load drill). ``deadline_s`` overrides the
    engine default per request (the burn drill submits with the SLO
    threshold as the deadline — clients give up at the objective)."""
    names = list(pools)
    futures, rejected = [], 0
    lat = {t: [] for t in names}
    start = time.monotonic()
    stop = start + duration
    next_t = start
    i = 0
    swap_info = None
    swap_thread = None
    while time.monotonic() < stop:
        now = time.monotonic()
        if (swap_fn is not None and swap_info is None
                and now - start >= swap_at):
            # Publish from a SIDE thread — the control plane is not the
            # request path, and a publish that blocked arrivals would
            # understate the offered load it is drilled under.
            swap_info = {
                "at_s": round(now - start, 3),
                "inflight_at_swap": engine.batcher.queue_depth,
            }

            def _publish(info=swap_info):
                t0 = time.monotonic()
                try:
                    info["params_version"] = swap_fn()
                except Exception as e:  # noqa: BLE001 — drill must report, not die
                    info["error"] = repr(e)
                info["publish_s"] = round(time.monotonic() - t0, 4)

            swap_thread = threading.Thread(target=_publish)
            swap_thread.start()
            continue
        if now < next_t:
            time.sleep(min(next_t - now, 0.01))
            continue
        next_t += rng.exponential(1.0 / rate)
        tenant = names[i % len(names)]
        pool = pools[tenant]
        inst = pool[int(rng.integers(len(pool)))]
        try:
            futures.append((tenant, engine.submit(
                inst, deadline_s=deadline_s, tenant=tenant,
            )))
        except Exception:  # noqa: BLE001 — Saturated backpressure
            rejected += 1
        i += 1
    t_end = time.monotonic()
    if swap_thread is not None:
        swap_thread.join(timeout=60.0)
    deadline_miss = dropped = 0
    for tenant, fut in futures:
        try:
            # The verdict's own latency_ms (enqueue -> verdict), not the
            # time of this post-hoc result() call — futures resolve while
            # the arrival loop is still generating.
            lat[tenant].append(fut.result(timeout=30.0)["latency_ms"] / 1e3)
        except TimeoutError:  # DeadlineExceeded subclasses TimeoutError
            deadline_miss += 1
        except Exception:  # noqa: BLE001 — anything else IS a dropped query
            dropped += 1
    wall = t_end - start
    return lat, rejected, deadline_miss, dropped, wall, i, swap_info


def pct(lat, q):
    if not lat:
        return float("nan")
    s = sorted(lat)
    return s[min(len(s) - 1, max(0, int(round(q / 100 * len(s))) - 1))] * 1e3


def pct_ms(lat, q):
    """Artifact-safe percentile: None (valid JSON) when the list is empty
    — a fully-shed tenant or fully-rejected phase must not write NaN into
    SERVE_r*.json."""
    return round(pct(lat, q), 2) if lat else None


def _flat(lat_by_tenant: dict) -> list:
    return [x for lats in lat_by_tenant.values() for x in lats]


def _per_tenant(lat_by_tenant: dict) -> dict:
    return {
        t: {
            "served": len(lats),
            "p50_ms": pct_ms(lats, 50),
            "p99_ms": pct_ms(lats, 99),
        }
        for t, lats in sorted(lat_by_tenant.items())
    }


def drive_one(engine, args, rng, swap_fn=None) -> dict:
    """Full load sequence against one engine: parity per tenant, warmup,
    closed + open phases, then the hot-swap drill as its OWN open-loop
    phase. Returns the result dict for this scheduler arm.

    The drill phase is deliberately separate from the measured A/B
    phases: the publish re-distills every slot on the same device the
    query programs run on, so overlapping it with a measured phase
    attributes publish contention to the scheduler under test (measured:
    it doubled the open-loop p99 of whichever arm it ran in)."""
    tenants = register_tenants(engine, args)
    compiled = engine.warmup()
    print(f"warmup: {compiled} bucket programs "
          f"(buckets={list(engine.batcher.buckets)}, "
          f"tenants={len(tenants)}, scheduler={engine.scheduler})",
          file=sys.stderr)

    parity = {}
    for tenant, ds in tenants.items():
        delta = check_registry_parity(engine, ds, tenant=tenant)
        parity[tenant] = delta
        print(f"parity[{tenant}]: registry vs direct forward "
              f"max|delta| = {delta:.2e}", file=sys.stderr)

    pools = _pools(tenants, args.K)
    out = {
        "scheduler": engine.scheduler,
        "parity_max_delta": {t: float(d) for t, d in parity.items()},
        "warmup_compiles": compiled,
    }
    if any(not (d < 1e-4) for d in parity.values()):
        out["parity_ok"] = False
        return out
    out["parity_ok"] = True

    if args.mode in ("closed", "both"):
        lat, errs, wall, retries = run_closed(
            engine, pools, args.concurrency, args.duration, rng
        )
        flat = _flat(lat)
        out["closed"] = {
            "concurrency": args.concurrency,
            "qps": round(len(flat) / wall, 1),
            "p50_ms": pct_ms(flat, 50),
            "p99_ms": pct_ms(flat, 99),
            "errors": errs,
            # Backoff honesty (ISSUE 12 satellite): how often each
            # tenant's workers were told to retry-after and slept.
            "retries": sum(retries.values()),
            "retries_per_tenant": dict(sorted(retries.items())),
            "per_tenant": _per_tenant(lat),
        }
    if args.mode in ("open", "both"):
        lat, rej, miss, dropped, wall, offered, _ = run_open(
            engine, pools, args.rate, args.duration, rng,
        )
        flat = _flat(lat)
        out["open"] = {
            "offered_qps": round(offered / wall, 1),
            "qps": round(len(flat) / wall, 1),
            "p50_ms": pct_ms(flat, 50),
            "p99_ms": pct_ms(flat, 99),
            "rejected": rej, "deadline_miss": miss, "dropped": dropped,
            "per_tenant": _per_tenant(lat),
        }
    if swap_fn is not None:
        drill_s = max(2.0, args.duration / 2)
        lat, rej, miss, dropped, wall, offered, swap_info = run_open(
            engine, pools, args.rate, drill_s, rng,
            swap_at=drill_s / 2, swap_fn=swap_fn,
        )
        flat = _flat(lat)
        swap_info.update({
            "offered_qps": round(offered / wall, 1),
            "served": len(flat),
            "p50_ms": pct_ms(flat, 50),
            "p99_ms": pct_ms(flat, 99),
            "rejected": rej, "deadline_miss": miss, "dropped": dropped,
        })
        out["swap_drill"] = swap_info

    snap = engine.stats.snapshot(queue_depth=engine.batcher.queue_depth)
    out["stats"] = snap
    out["per_tenant_stats"] = engine.stats.tenant_snapshot()
    # Per-arm trace summary (ISSUE 9): segment-breakdown medians +
    # exemplar trace_ids over the sampled requests of THIS arm's engine —
    # the artifact-side attribution of where each scheduler spends a
    # request's latency (full waterfalls: obs_report on --run_dir).
    out["trace"] = engine.stats.trace_summary()
    if args.burn_drill:
        # LAST, after the measured numbers are snapshotted: the drill
        # deliberately overloads the engine and would pollute every
        # percentile recorded after it.
        out["burn_drill"] = run_burn_drill(engine, pools, args, rng)
    return out


def run_burn_drill(engine, pools, args, rng) -> dict:
    """Overload phase: ESCALATING open-loop arrival rates drive latency
    (and, at the top multipliers, queue rejections) through the SLO
    objective; the fast-window burn must trip a once-latched CRITICAL
    whose diagnostics auto-capture is on disk before this returns.

    The latency objective is 2x the arm's measured p50 — an honest
    threshold the healthy phases satisfied, so the trip is caused by the
    overload, not by an impossible objective. Drill submits carry the
    threshold as their DEADLINE (clients give up at the objective), so a
    queue-delayed request burns budget as a deadline miss even when the
    device itself stays fast. Escalation (4x/16x/64x the configured
    rate, ~1.2 s each, stop at first trip) makes the drill
    machine-speed-independent: a host fast enough to absorb one
    multiplier cleanly meets the next one."""
    from induction_network_on_fewrel_tpu.obs.health import SLOObjective

    slo = engine.slo
    baseline_p50 = engine.stats.percentile_ms(50) or 5.0
    thr = args.slo_latency_ms or round(max(1.0, 2.0 * baseline_p50), 3)
    slo.default_objective = SLOObjective(
        availability=args.slo_availability, latency_ms=thr
    )
    phase_s = max(1.2, args.duration / 4)
    totals = {"offered": 0, "served": 0, "rejected": 0,
              "deadline_miss": 0, "dropped": 0}
    all_lat: dict[str, list] = {t: [] for t in pools}
    tripped_at = None
    for mult in (4, 16, 64):
        rate = max(args.rate * mult, 100.0)
        print(f"burn drill: rate {rate}/s for {phase_s}s against "
              f"latency SLO {thr} ms (fast window {args.slo_fast_s}s)",
              file=sys.stderr)
        lat, rej, miss, dropped, wall, offered, _ = run_open(
            engine, pools, rate, phase_s, rng, deadline_s=thr / 1e3,
        )
        for t, xs in lat.items():
            all_lat[t].extend(xs)
        totals["offered"] += offered
        totals["served"] += sum(len(x) for x in lat.values())
        totals["rejected"] += rej
        totals["deadline_miss"] += miss
        totals["dropped"] += dropped
        slo.evaluate()
        if any(e.event == "slo_fast_burn" for e in slo.events):
            tripped_at = mult
            break
    fast = [e for e in slo.events if e.event == "slo_fast_burn"]
    # Once-latch: a second sweep while still burning must emit nothing new.
    relatch = slo.evaluate()
    flat = _flat(all_lat)
    return {
        "threshold_ms": thr,
        "tripped_at_rate_multiplier": tripped_at,
        "p99_ms": pct_ms(flat, 99),
        **totals,
        "tripped": slo.tripped,
        "fast_burn_events": len(fast),
        "once_latched": len(relatch) == 0,
        "burn_rates": {
            t: slo.burn_rates(t) for t in sorted(all_lat)
            if slo.burn_rates(t) is not None
        },
        "captures": {
            latch: {
                k: cap.get(k) for k in
                ("flight_dump", "span_snapshot", "profile", "profile_state")
            }
            for latch, cap in slo.captured.items()
        },
    }


def _oov_instance(i: int = 0):
    """A constant out-of-vocabulary query: every token misses the GloVe
    vocab and maps to UNK, so repeated submissions are a POINT MASS in
    logit space — which makes the drill's calibrated floor split the
    clean pool from the probe deterministically (a point mass is always
    strictly on one side of a threshold)."""
    from induction_network_on_fewrel_tpu.data.fewrel import Instance

    toks = tuple(f"zqxdrift{i}" for _ in range(8))
    return Instance(tokens=toks, head_pos=(0,), tail_pos=(1,))


def _nota_gap(verdict: dict) -> float:
    """The scalar the engine's NOTA decision thresholds on, verdict-side:
    with a trained NOTA head (na_rate>0 checkpoints) the verdict is NOTA
    iff ``thr > best - nota_logit``; with the open-set floor it is NOTA
    iff ``thr > best``. Both are "NOTA iff gap < thr" on THIS gap, so
    the drill's floor calibration works identically for either kind of
    checkpoint."""
    from induction_network_on_fewrel_tpu.serving.engine import NO_RELATION

    best = max(
        v for k, v in verdict["logits"].items() if k != NO_RELATION
    )
    if NO_RELATION in verdict["logits"]:
        return best - verdict["logits"][NO_RELATION]
    return best


def calibrate_drift_floor(in_gaps, oov_gaps) -> dict:
    """Pick the NOTA threshold + the "clean pool" that make the drill
    DETERMINISTIC. Inputs are per-verdict ``_nota_gap`` values (the
    scalar the engine thresholds on — best class logit, minus the NOTA
    logit when a trained head exists, so the calibration is correct for
    BOTH checkpoint kinds): the OOV probe is a point mass at ``v`` (one
    constant instance -> one gap), so a threshold strictly between ``v``
    and the in-domain gaps on the more-populated side of it gives a
    baseline NOTA rate of exactly 0 (or exactly 1, when most in-domain
    gaps sit BELOW v — drift is |delta|, both directions trip) and a
    shifted rate of exactly 1 (or 0). No sampling noise: the injected
    shift moves the windowed rate by 1.0, and clean post-publish traffic
    from the clean pool reproduces the baseline rate exactly.

    Returns {threshold, clean_idx (indices into in_gaps for the
    baseline/clean phases), clean_frac, base_rate, shift_rate}."""
    import numpy as np

    in_l = np.asarray(in_gaps, dtype=np.float64)
    v = float(np.median(np.asarray(oov_gaps, dtype=np.float64)))
    eps = max(1e-9, 1e-6 * max(abs(v), 1.0))
    above = np.flatnonzero(in_l > v + eps)
    below = np.flatnonzero(in_l < v - eps)
    if len(above) == 0 and len(below) == 0:
        return {"threshold": None, "clean_idx": [], "clean_frac": 0.0,
                "base_rate": None, "shift_rate": None}
    if len(above) >= len(below):
        # Floor between v and the smallest clean-pool gap: clean pool
        # never verdicts NOTA (rate 0), the OOV point mass always does.
        thr = (v + float(in_l[above].min())) / 2.0
        clean, base_rate, shift_rate = above, 0.0, 1.0
    else:
        thr = (float(in_l[below].max()) + v) / 2.0
        clean, base_rate, shift_rate = below, 1.0, 0.0
    return {
        "threshold": round(thr, 6),
        "clean_idx": [int(i) for i in clean],
        # Honest coverage: the fraction of the in-domain pool the floor
        # classifies deterministically (the minority side straddling v
        # is EXCLUDED from drill traffic, not misreported as separated).
        "clean_frac": round(len(clean) / max(len(in_l), 1), 4),
        "base_rate": base_rate,
        "shift_rate": shift_rate,
    }


def run_drift_drill(args, ckpt, logger, recorder, capture) -> dict:
    """The ISSUE 10 model-quality drill, on its own engine (the injected
    shift would pollute every measured arm's quality stream):

    1. probe — in-domain + constant-OOV traffic; calibrate the open-set
       NOTA floor from the verdicts' NOTA gaps (deterministic split).
    2. baseline — re-arm the detector, then in-domain traffic until the
       calibration baseline captures and the detection window fills.
    3. shift — OOV traffic; the NOTA rate (and typically margin/entropy)
       must shift past the critical band: once-latched CRITICAL with
       diagnostics on disk.
    4. once-latch — more shifted traffic emits nothing new.
    5. publish re-arm — hot-swap the engine's own params; the detector
       re-arms (a publish legitimately moves the distribution), then
       clean in-domain traffic re-baselines without tripping.
    """
    from induction_network_on_fewrel_tpu.obs import DriftDetector

    tenant = "tenant0"
    drift = DriftDetector(
        window=64, baseline_n=48, min_count=24,
        eval_interval_s=0.0,          # drill: judge every observation
        logger=logger, recorder=recorder, capture=capture,
    )
    engine = build_engine(args, ckpt, "continuous", logger=logger,
                          drift=drift)
    out: dict = {}
    try:
        tenants = register_tenants(engine, args)
        engine.warmup()
        pool = _pools(tenants, args.K)[tenant]
        oov = _oov_instance()

        def classify_many(insts) -> list[dict]:
            return [engine.classify(i, tenant=tenant) for i in insts]

        # 1. probe + floor calibration (pre-baseline: everything the
        # probe feeds the detector is discarded by the re-arm below).
        # Each pool instance is probed ONCE — its logit is a constant,
        # so the calibrated clean pool has a deterministic NOTA rate.
        probe_in = classify_many(pool)
        probe_oov = classify_many([oov] * 3)
        cal = calibrate_drift_floor(
            [_nota_gap(v) for v in probe_in],
            [_nota_gap(v) for v in probe_oov],
        )
        out["calibration"] = {
            k: cal[k] for k in
            ("threshold", "base_rate", "shift_rate", "clean_frac")
        }
        out["clean_pool"] = len(cal["clean_idx"])
        if cal["threshold"] is None or not cal["clean_idx"]:
            out["tripped"] = False
            return out
        clean = [pool[i] for i in cal["clean_idx"]]
        # Setting the threshold re-arms the tenant's drift baseline
        # automatically (a control-plane change legitimately moves the
        # distribution — engine._drift_rearm), discarding the probe
        # traffic's state.
        engine.set_nota_threshold(cal["threshold"], tenant=tenant)
        out["rearmed_on_calibration"] = not drift.armed(tenant)
        # Drill accounting starts HERE: a large pool can arm the
        # detector DURING the probe phase and latch something on probe
        # traffic (legitimately — it is real drift vs the probe mix);
        # those pre-calibration events and the sticky `tripped` flag
        # must not leak into the verdict, so every assertion below
        # slices the event history from this point.
        drill_start = len(drift.events)

        def drill_events():
            return list(drift.events)[drill_start:]

        # 2. fresh baseline under the calibrated floor, from the clean
        # pool (deterministic NOTA rate; cycled so every phase sees the
        # same composition).
        n_base = drift.baseline_n + drift.min_count + 8
        classify_many(clean[i % len(clean)] for i in range(n_base))
        out["baseline_armed"] = drift.armed(tenant)
        out["baseline"] = drift.baseline_for(tenant)

        # 3. injected shift: constant-OOV traffic.
        tripped_after = None
        for i in range(drift.window):
            engine.classify(oov, tenant=tenant)
            if any(e.severity == "critical" for e in drill_events()):
                tripped_after = i + 1
                break
        crits = [e for e in drill_events() if e.severity == "critical"]
        out["tripped"] = bool(crits)
        out["tripped_after"] = tripped_after
        out["critical_events"] = len(crits)
        out["drift_features"] = sorted({
            e.data.get("feature") for e in crits
        })
        out["state_at_trip"] = drift.drift_state(tenant)

        # 4. once-latch: continued shift re-fires nothing — at most ONE
        # critical per (tenant, feature); a second FEATURE latching late
        # (margin often follows nota_rate) is a new latch, not a re-fire.
        from collections import Counter

        classify_many([oov] * drift.min_count)
        per_feature = Counter(
            e.data.get("feature") for e in drill_events()
            if e.severity == "critical"
        )
        out["once_latched"] = bool(per_feature) and all(
            v == 1 for v in per_feature.values()
        )
        out["captures"] = {
            latch: {k: cap.get(k) for k in
                    ("flight_dump", "span_snapshot", "profile_state")}
            for latch, cap in drift.captured.items()
        }

        # 5. publish re-arms; clean traffic re-baselines quietly. The
        # NOTA rate is deterministic over the clean pool, so no
        # nota_rate event may fire and nothing may go CRITICAL;
        # margin/entropy warnings from clean-pool composition cycling
        # are tolerated (recorded, not failed).
        rearms_before = drift.rearms
        version = engine.publish_params(engine.params)
        out["published_version"] = version
        out["rearmed_on_publish"] = (
            drift.rearms == rearms_before + 1
            and not drift.armed(tenant)
        )
        events_before = [
            e for e in drift.events if e.event == "prediction_drift"
        ]
        classify_many(clean[i % len(clean)] for i in range(n_base))
        new_events = [
            e for e in drift.events if e.event == "prediction_drift"
        ][len(events_before):]
        out["rebaselined"] = drift.armed(tenant)
        out["post_publish_events"] = len(new_events)
        out["clean_after_publish"] = not any(
            e.severity == "critical"
            or e.data.get("feature") == "nota_rate"
            for e in new_events
        )
        engine.emit_stats()   # kind="quality" records land in metrics.jsonl
        return out
    finally:
        engine.close()


def _chaos_ckpt_leg(logger) -> dict:
    """kill -> corrupt-newest-ring-slot -> resume, in-process: a tiny
    lazy-embed run writes base + delta ring slots (with cursor sidecars),
    the newest slot is corrupted on disk, and a fresh CheckpointManager —
    exactly what ``--resume`` builds — must quarantine it and restore the
    newest INTACT slot bitwise, with the cursor sidecar following."""
    import jax
    import numpy as np

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.build import (
        batch_to_model_inputs,
    )
    from induction_network_on_fewrel_tpu.obs.chaos import corrupt_step_dir
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.steps import (
        init_state,
        make_train_step,
    )

    cfg = ExperimentConfig(
        encoder="cnn", n=3, k=2, q=2, batch_size=2, max_length=12,
        vocab_size=202, hidden_size=16, embed_optimizer="lazy",
        compute_dtype="float32", ckpt_stage="off", device="cpu",
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=6, instances_per_relation=6, vocab_size=35
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(
        ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=3
    )
    batches = [
        batch_to_model_inputs(sampler.sample_batch()) for _ in range(6)
    ]
    model = build_model(cfg, glove_init=vocab.vectors)
    step_fn = make_train_step(model, cfg)
    state = init_state(model, cfg, batches[0][0], batches[0][1])
    # np.array COPIES here too: on the CPU backend device_get returns
    # views of device buffers, and the donating train steps below reuse
    # that memory — a template whose leaves mutate under the restore
    # would silently re-type it.
    template = jax.tree.map(lambda x: np.array(x), jax.device_get(state))

    from pathlib import Path

    work = tempfile.mkdtemp(prefix="chaos_ckpt_")
    mgr = CheckpointManager(work, cfg, logger=logger)
    for sup, qry, lab in batches[:2]:
        state, _ = step_fn(state, sup, qry, lab)
    mode_base = mgr.save_latest(2, state, cursor={"pos": 2})["mode"]
    mgr.wait()
    # np.array COPIES: on the CPU backend device_get returns views of
    # the device buffers, and the donating train steps below would reuse
    # that memory — the "surviving state" must not mutate under us.
    survivor = jax.tree.map(lambda x: np.array(x), jax.device_get(state))
    for sup, qry, lab in batches[2:4]:
        state, _ = step_fn(state, sup, qry, lab)
    mode_delta = mgr.save_latest(4, state, cursor={"pos": 4})["mode"]
    mgr.wait()
    mgr.close()    # the "kill": the process owning the run is gone

    corrupted = corrupt_step_dir(Path(work) / "ring_delta" / "4", "bitflip")
    mgr2 = CheckpointManager(work, cfg, logger=logger)   # the "--resume"
    restored, step = mgr2.restore_latest(template)
    mismatched = []
    for (pa, va), (_, vb) in zip(
        jax.tree_util.tree_flatten_with_path(survivor)[0],
        jax.tree_util.tree_flatten_with_path(restored)[0],
    ):
        if not np.array_equal(np.asarray(va), np.asarray(vb)):
            mismatched.append(jax.tree_util.keystr(pa))
    bitwise = not mismatched
    cursor = mgr2.load_cursor(step)
    quarantined = sorted(
        str(p.relative_to(work))
        for p in Path(work).rglob("*.quarantined*")
    )
    mgr2.close()
    return {
        "modes": [mode_base, mode_delta],
        "corrupted_file": corrupted,
        "fallback_step": step,
        "bitwise_equal": bitwise,
        # Which leaves diverged, when any did — a failing drill must name
        # the evidence, not just say "False".
        "mismatched_leaves": mismatched[:8],
        "cursor_followed": bool(cursor) and cursor.get("pos") == step,
        "quarantined": quarantined,
    }


def run_chaos_drill(args, ckpt, logger, recorder, capture) -> dict:
    """The ISSUE 12 fault-domain drill, on its own engine:

    1. execute faults — injected launch failures for tenant0 fail ONLY
       that batch's futures (typed ExecuteError) and trip its circuit
       breaker (once-latched CRITICAL breaker_open); the other tenant
       keeps serving; after the open window a half-open probe recovers
       the tenant (breaker closed, latch re-armed).
    2. poisoned publish — an injected NaN publish is refused by the
       pre-swap validation gate and ROLLS BACK: registry generation
       unchanged, every tenant on its old snapshot, zero dropped
       in-flight requests, zero steady-state recompiles; CRITICAL
       publish_rollback once.
    3. recovery — a clean publish commits (rollback latch re-arms,
       drift baseline re-arms) and the tenant's SLO fast-burn latch
       re-arms once clean traffic drains the window.
    4. corrupted ring slot — kill/corrupt/resume recovers bitwise from
       the newest intact slot (``_chaos_ckpt_leg``).
    """
    from induction_network_on_fewrel_tpu.obs import (
        DriftDetector,
        HealthWatchdog,
        SLOEngine,
        SLOObjective,
    )
    from induction_network_on_fewrel_tpu.obs.chaos import ChaosRegistry, install
    from induction_network_on_fewrel_tpu.serving.batcher import (
        ExecuteError,
        Saturated,
    )
    from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker
    from induction_network_on_fewrel_tpu.serving.registry import PublishError

    THRESHOLD, OPEN_S, FAST_S = 3, 0.6, 0.75
    watchdog = HealthWatchdog(
        logger=logger, recorder=recorder, capture=capture
    )
    if logger is not None:
        logger.add_hook(watchdog.observe_record)
    chaos = ChaosRegistry.parse(
        f"serve.execute_raise@0*{THRESHOLD}:tenant0,publish.nan_params@0",
        logger=logger,
    )
    chaos.install()
    breaker = CircuitBreaker(failure_threshold=THRESHOLD, open_s=OPEN_S)
    slo = SLOEngine(
        SLOObjective(availability=args.slo_availability,
                     latency_ms=args.slo_latency_ms),
        fast_window_s=FAST_S, slow_window_s=10 * FAST_S,
        logger=logger, recorder=recorder, capture=capture,
    )
    drift = DriftDetector(
        window=16, baseline_n=8, min_count=8, eval_interval_s=0.0,
        logger=logger, recorder=recorder, capture=capture,
    )
    engine = build_engine(args, ckpt, "continuous", logger=logger,
                          slo=slo, drift=drift, breaker=breaker)
    out: dict = {"threshold": THRESHOLD, "open_s": OPEN_S}
    try:
        tenants = register_tenants(engine, args)
        engine.warmup()
        pools = _pools(tenants, args.K)
        t0 = "tenant0"
        others = [t for t in pools if t != t0]

        # 1. execute faults -> typed errors -> breaker opens -> shed.
        exec_errors = shed = 0
        for i in range(12):
            try:
                engine.classify(pools[t0][i % len(pools[t0])], tenant=t0)
            except ExecuteError:
                exec_errors += 1
            except Saturated:
                shed += 1
        out["execute_errors"] = exec_errors
        out["shed_while_open"] = shed
        out["breaker_opened"] = breaker.state(t0) == "open"
        other_served = 0
        for t in others:
            for i in range(4):
                v = engine.classify(pools[t][i % len(pools[t])], tenant=t)
                other_served += "label" in v and not v.get("degraded", False)
        out["other_tenant_served"] = other_served
        crits = [e for e in watchdog.events
                 if e.event == "breaker_open" and e.severity == "critical"]
        out["breaker_open_criticals"] = len(crits)

        # Recovery: half-open probe after the window.
        time.sleep(OPEN_S + 0.1)
        v = engine.classify(pools[t0][0], tenant=t0)
        out["probe_served"] = "label" in v
        out["breaker_recovered"] = breaker.state(t0) == "closed"

        # 2. poisoned publish under in-flight load.
        pv0 = engine.registry.params_version
        versions0 = {
            t: engine.registry.snapshot(t).version
            for t in engine.registry.tenants()
        }
        futs = []
        for i in range(16):
            t = list(pools)[i % len(pools)]
            futs.append(engine.submit(
                pools[t][i % len(pools[t])], tenant=t
            ))
        try:
            engine.publish_params(engine.params)
            poisoned_raised = False
        except PublishError as e:
            poisoned_raised = True
            out["rollback_reason"] = str(e)[:160]
        dropped = 0
        for f in futs:
            try:
                f.result(timeout=30.0)
            except Exception:  # noqa: BLE001 — any failure IS a drop here
                dropped += 1
        snap = engine.stats.snapshot()
        out["rollback"] = {
            "poisoned_publish_refused": poisoned_raised,
            "params_version_before": pv0,
            "params_version_after": engine.registry.params_version,
            "tenant_snapshots_unchanged": versions0 == {
                t: engine.registry.snapshot(t).version
                for t in engine.registry.tenants()
            },
            "dropped_during_rollback": dropped,
            "steady_recompiles": snap["steady_recompiles"],
            "rollback_criticals": sum(
                1 for e in watchdog.events
                if e.event == "publish_rollback"
            ),
        }

        # 3. clean publish commits: drift + rollback latch re-arm; SLO
        # fast-burn latch re-arms once clean traffic drains the window.
        rearms_before = drift.rearms
        out["clean_publish_version"] = engine.publish_params(engine.params)
        out["drift_rearmed"] = drift.rearms == rearms_before + 1
        out["rollback_latch_rearmed"] = (
            "publish_rollback" not in watchdog._latched
        )
        slo.evaluate()
        out["slo_tripped_during_faults"] = slo.tripped
        time.sleep(FAST_S + 0.2)
        for i in range(15):
            engine.classify(pools[t0][i % len(pools[t0])], tenant=t0)
        slo.evaluate()
        out["slo_rearmed"] = f"slo_burn:{t0}:fast" not in slo._latched
        out["stats"] = engine.stats.snapshot(
            queue_depth=engine.batcher.queue_depth
        )
    finally:
        engine.close()
        install(None)

    # 4. kill -> corrupt -> resume (its own tiny training world).
    out["ckpt"] = _chaos_ckpt_leg(logger)
    out["ckpt_corrupt_criticals"] = sum(
        1 for e in watchdog.events if e.event == "ckpt_corrupt"
    )
    out["injected"] = len(chaos.fired_log)
    return out


def check_chaos_drill(drill: dict) -> bool:
    """The drill's acceptance: inject -> contain -> recover, all held."""
    rb = drill.get("rollback", {})
    return bool(
        drill.get("breaker_opened")
        and drill.get("breaker_open_criticals") == 1
        and drill.get("execute_errors", 0) >= 1
        and drill.get("other_tenant_served", 0) >= 1
        and drill.get("probe_served")
        and drill.get("breaker_recovered")
        and rb.get("poisoned_publish_refused")
        and rb.get("params_version_before") == rb.get("params_version_after")
        and rb.get("tenant_snapshots_unchanged")
        and rb.get("dropped_during_rollback") == 0
        and rb.get("steady_recompiles") == 0
        and rb.get("rollback_criticals") == 1
        and drill.get("drift_rearmed")
        and drill.get("rollback_latch_rearmed")
        and drill.get("slo_rearmed")
        and drill.get("ckpt", {}).get("bitwise_equal")
        and drill.get("ckpt", {}).get("cursor_followed")
        and drill.get("ckpt", {}).get("quarantined")
        and drill.get("ckpt_corrupt_criticals", 0) >= 1
    )


# --- self-healing adaptation drill (ISSUE 14) -------------------------------

# The miniature adaptation world: the smallest config where the
# SCENARIOS_r01 story reproduces end to end on CPU in seconds — a
# source-trained model collapses to the all-NOTA basin on the shifted
# twin (tgt traffic ~0.9 NOTA through the serving engine), and a
# mixture-ramp fine-tune recovers it (tgt NOTA back to the in-domain
# 0.0). CE loss + seed 1 per the scenarios TIER1 rationale.
ADAPT_WORLD = dict(
    num_relations=5, instances_per_relation=20,
    train_iters=140, finetune_steps=100,
    # grid_5w2s (ISSUE 19): the canary also runs an (N, K) grid point at
    # a DIFFERENT geometry than the fine-tune's (5-way vs the 2-way
    # training geometry) — an adaptation that recovers the flagship
    # geometry but regresses another grid point must not publish. Floor
    # sits well above 5-way chance (0.2) but far below the source-trained
    # model's measured 5w2s accuracy (0.95 at canary seed, 48 episodes).
    canary_floors={"in_domain": 0.6, "target": 0.5, "grid_5w2s": 0.3},
    canary_episodes=48,
    drift=dict(window=32, baseline_n=24, min_count=16),
    cfg=dict(
        model="induction", encoder="cnn", hidden_size=64,
        induction_dim=32, ntn_slices=32, routing_iters=2,
        train_n=2, n=2, k=2, q=2, na_rate=1, batch_size=4,
        max_length=16, vocab_size=302, word_dim=50,
        compute_dtype="float32", loss="ce", lr=5e-3,
        weight_decay=0.0, val_step=0, device="cpu", seed=1,
    ),
)


def _adapt_world(seed: int, tmpdir: str):
    """(cfg, tok, model, src, tgt, ckpt_dir): the source-trained live
    artifact plus the two corpora. The tgt twin is the same relations
    with the trigger signal moved to a disjoint vocab block
    (make_domain_shifted_fewrel — wiki -> pubmed in miniature)."""
    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_domain_shifted_fewrel,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
    from induction_network_on_fewrel_tpu.train import FewShotTrainer
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    plan = ADAPT_WORLD
    cfg = ExperimentConfig(**plan["cfg"])
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    src = make_synthetic_fewrel(
        num_relations=plan["num_relations"],
        instances_per_relation=plan["instances_per_relation"],
        vocab_size=cfg.vocab_size - 2, seed=seed,
    )
    tgt = make_domain_shifted_fewrel(
        num_relations=plan["num_relations"],
        instances_per_relation=plan["instances_per_relation"],
        vocab_size=cfg.vocab_size - 2, shift=1.0, seed=seed,
    )
    model = build_model(cfg, glove_init=vocab.vectors)
    trainer = FewShotTrainer(
        model, cfg,
        EpisodeSampler(src, tok, n=cfg.n, k=cfg.k, q=cfg.q,
                       batch_size=cfg.batch_size, na_rate=cfg.na_rate,
                       seed=seed + 1),
        logger=MetricsLogger(quiet=True),
    )
    state = trainer.train(num_iters=plan["train_iters"])
    ckpt = os.path.join(tmpdir, "live_ckpt")
    mngr = CheckpointManager(ckpt, cfg, stage="off")
    try:
        mngr.save(plan["train_iters"], state, val_accuracy=0.0)
        mngr.wait()
    finally:
        mngr.close()
    trainer.close()
    return cfg, tok, model, src, tgt, ckpt


def _adapt_pools(src, tgt, k: int):
    """Held-out (post-support) query pools per domain."""
    return (
        [i for r in src.rel_names for i in src.instances[r][k:]],
        [i for r in tgt.rel_names for i in tgt.instances[r][k:]],
    )


def _build_adapt_controller(model, cfg, tok, src, tgt, ckpt, drift,
                            publish_fn, quarantine_fn, tmpdir, *,
                            steps, logger=None, recorder=None,
                            capture=None, **kw):
    """The drill's controller: real mixture fine-tune, real scenario-
    harness canary, the caller's (fan-out) publish. Mirrors the serve.py
    wiring (serving/cli._build_adapt) at drill scale."""
    from induction_network_on_fewrel_tpu.obs.adapt import (
        AdaptationController,
        make_checkpoint_loop,
    )
    from induction_network_on_fewrel_tpu.serving.registry import load_params
    from induction_network_on_fewrel_tpu.train.finetune import (
        mixture_finetune,
    )
    from scenarios import run_canary

    # Per-controller candidate dir: the two arms share one world (and
    # one tmpdir), and a failure-arm candidate must never collide with
    # the success arm's published one (orbax refuses step re-saves).
    work = tempfile.mkdtemp(dir=tmpdir, prefix="candidates_")

    def finetune(src_ckpt, out, seq, attempt, step_budget, wall_budget_s):
        return mixture_finetune(
            src_ckpt, out, src, tgt, tok, steps=step_budget,
            wall_budget_s=wall_budget_s, seed=cfg.seed + 100 + seq,
        )

    # The shared closure wiring (live-artifact holder, candidate
    # naming, publish/cleanup) is ONE home with serve.py's builder.
    train_fn, publish, cleanup, current_fn = make_checkpoint_loop(
        ckpt, work, finetune, publish_fn, prefix="cand_",
    )

    def canary_fn(candidate):
        # Geometry legs (ISSUE 19): every grid_<N>w<K>s floor spawns a
        # source-corpus leg at THAT episode geometry (run_canary parses
        # the name) — the publish gate holds the candidate to the whole
        # grid, not just the fine-tune's own geometry.
        floors = dict(ADAPT_WORLD["canary_floors"])
        legs = {"in_domain": src, "target": tgt}
        for name in floors:
            if name.startswith("grid_"):
                legs[name] = src
        return run_canary(
            model, load_params(candidate), cfg, tok,
            legs=legs, floors=floors,
            episodes=ADAPT_WORLD["canary_episodes"], seed=cfg.seed + 7,
        )

    controller = AdaptationController(
        train_fn, canary_fn, publish, drift=drift,
        current_fn=current_fn, cleanup_fn=cleanup,
        quarantine_fn=quarantine_fn, step_budget=steps,
        logger=logger, recorder=recorder, capture=capture, **kw,
    )
    return controller, work


def _drive_until(classify, pool, *, stop, cap, count_nota=False):
    """Classify pool instances round-robin until ``stop()`` or ``cap``
    calls; returns (calls, nota_count)."""
    nota = 0
    for i in range(cap):
        if stop():
            return i, nota
        v = classify(pool[i % len(pool)])
        nota += bool(v.get("nota")) if count_nota else 0
    return cap, nota


def run_adapt_success_arm(cfg, tok, model, src, tgt, ckpt, tmpdir,
                          logger=None, recorder=None, capture=None,
                          replicas: int = 3) -> dict:
    """Inject shift -> drift CRITICAL -> mixture fine-tune -> canary
    pass -> all-or-nothing fan-out publish (0 dropped, 0 steady
    recompiles, params_version uniform) -> NOTA rate back in band ->
    detector re-armed -> controller verified."""
    from induction_network_on_fewrel_tpu.fleet import (
        FleetControl,
        FleetRouter,
        InProcessReplica,
    )
    from induction_network_on_fewrel_tpu.obs import DriftDetector
    from induction_network_on_fewrel_tpu.obs.adapt import (
        COOLDOWN,
        TRIGGERED,
        VERIFYING,
    )
    from induction_network_on_fewrel_tpu.serving.engine import (
        InferenceEngine,
    )
    from induction_network_on_fewrel_tpu.serving.registry import load_params

    tenant = "tenant0"
    dknobs = ADAPT_WORLD["drift"]
    # ONE detector shared by every replica (per-tenant keyed): the
    # owner replica's verdicts feed it, and a committed fan-out re-arms
    # it exactly once (the first replica's commit hook drops the state;
    # the rest are quiet no-ops — pinned in tests/test_fleet.py).
    drift = DriftDetector(
        eval_interval_s=0.0, logger=logger, recorder=recorder,
        capture=capture, **dknobs,
    )
    params = load_params(ckpt)
    handles = {
        f"r{i}": InProcessReplica(
            f"r{i}",
            InferenceEngine(model, params, cfg, tok, k=cfg.k,
                            buckets=(1, 2, 4), logger=logger,
                            drift=drift),
        )
        for i in range(replicas)
    }
    router = FleetRouter(handles, logger=logger)
    control = FleetControl(router)
    out: dict = {"replicas": replicas}
    src_pool, tgt_pool = _adapt_pools(src, tgt, cfg.k)
    # The zero-drop proof rides INSIDE the publish: the wrapper submits
    # a burst of clean queries immediately before the fan-out, so the
    # hot-swap commits with requests genuinely in flight (the PR 7
    # pattern — serving load concurrent with TRAINING dispatch is a
    # separate, image-unsafe pattern: two threads driving jit on this
    # CPU build corrupt the heap, the round-6/round-10 ENV finding).
    inflight: dict = {"futures": [], "submitted": 0}

    def publish_with_inflight_load(candidate):
        futs = [
            router.submit(src_pool[i % len(src_pool)], 30.0,
                          tenant=tenant)
            for i in range(16)
        ]
        inflight["futures"].extend(futs)
        inflight["submitted"] += len(futs)
        return control.publish_checkpoint(candidate)

    controller, work = _build_adapt_controller(
        model, cfg, tok, src, tgt, ckpt, drift,
        publish_with_inflight_load,
        lambda t, reason="": control.quarantine_tenant(t, reason=reason),
        tmpdir, steps=ADAPT_WORLD["finetune_steps"],
        logger=logger, recorder=recorder, capture=capture,
        retry_budget=3, backoff_s=0.5, cooldown_s=5.0,
        verify_window_s=120.0, wall_budget_s=120.0,
    )
    try:
        control.register_tenant(tenant, src)
        for h in router.replicas.values():
            h.warmup()

        def classify(inst):
            return router.classify(inst, 30.0, tenant=tenant)

        # 1. Calibration baseline from clean in-domain traffic.
        n_base = dknobs["baseline_n"] + dknobs["min_count"] + 8
        _drive_until(classify, src_pool, stop=lambda: False, cap=n_base)
        out["baseline_armed"] = drift.armed(tenant)
        healthy = drift.baseline_for(tenant)
        out["nota_healthy"] = healthy["nota_rate"][0] if healthy else None

        # 2. Inject the domain shift: target-twin traffic. The NOTA
        # collapse must trip a CRITICAL which triggers the controller
        # (drift.on_event -> controller.trigger). A FIXED window of
        # shifted queries (not stop-at-trigger): the trip usually lands
        # within a few queries — margin/entropy move first — and the
        # recorded shifted NOTA rate must measure the collapse itself,
        # not the trip latency; extra triggers are absorbed.
        calls, nota_shift = _drive_until(
            classify, tgt_pool, stop=lambda: False,
            cap=2 * dknobs["window"], count_nota=True,
        )
        out["tripped"] = controller.state_of(tenant) == TRIGGERED
        out["shift_queries"] = calls
        out["nota_shifted"] = round(nota_shift / max(calls, 1), 4)
        trigger_recs = [r for r in controller.records
                        if r["action"] == "trigger"]
        out["trigger_feature"] = (
            trigger_recs[0].get("feature") if trigger_recs else None
        )
        if not out["tripped"]:
            out["verified"] = False
            return out

        # 3. The adaptation attempt — fine-tune + canary + fan-out
        # publish with the in-flight burst (the publish wrapper above):
        # zero dropped, zero steady recompiles, params_version uniform.
        versions0 = {
            rid: h.engine.registry.params_version
            for rid, h in handles.items()
        }
        t0 = time.monotonic()
        processed = controller.run_once()
        out["adapt_wall_s"] = round(time.monotonic() - t0, 3)
        out["processed"] = processed
        out["state_after_publish"] = controller.state_of(tenant)
        out["published"] = controller.state_of(tenant) == VERIFYING
        recs = {r["action"]: r for r in controller.records}
        out["finetune_s"] = recs.get("train", {}).get("train_s")
        out["canary_passed"] = recs.get("canary", {}).get("passed") == 1.0
        out["publish_s"] = recs.get("publish", {}).get("publish_s")
        dropped = 0
        for fut in inflight["futures"]:
            try:
                fut.result(timeout=30.0)
            except Exception:  # noqa: BLE001 — any failure IS a drop
                dropped += 1
        out["inflight_at_publish"] = inflight["submitted"]
        out["dropped_during_publish"] = dropped
        versions1 = {
            rid: h.engine.registry.params_version
            for rid, h in handles.items()
        }
        out["params_version_before"] = sorted(versions0.values())[0]
        out["params_versions_after"] = sorted(set(versions1.values()))
        out["versions_uniform"] = (
            len(set(versions1.values())) == 1
            and all(versions1[r] == versions0[r] + 1 for r in versions1)
        )
        out["steady_recompiles"] = sum(
            h.engine.stats.snapshot()["steady_recompiles"]
            for h in handles.values()
        )

        # 4. Post-publish verification: the shifted domain IS the new
        # normal — adapted, its traffic must re-baseline the re-armed
        # detector with the NOTA rate back in band of the healthy
        # baseline, and the controller declares success. EXACTLY
        # baseline_n queries: the recaptured baseline is the verify
        # check's input, and stopping short of min_count further window
        # fill keeps clean-pool composition oscillation (a real margin-
        # window effect on an 80-instance pool) from judging anything
        # mid-verification.
        rearms_at_publish = drift.rearms
        _drive_until(classify, tgt_pool, stop=lambda: False,
                     cap=dknobs["baseline_n"])
        post_base = drift.baseline_for(tenant)
        out["nota_post"] = (
            post_base["nota_rate"][0] if post_base else None
        )
        out["rearmed"] = drift.armed(tenant) and rearms_at_publish >= 1
        controller.tick()
        out["verified"] = controller.state_of(tenant) == COOLDOWN
        ver = [r for r in controller.records if r["action"] == "verified"]
        if ver:
            out["recover_s"] = ver[-1].get("recover_s")
            out["nota_band"] = ver[-1].get("nota_band")
        out["loops"] = controller.loop_info(tenant)["loops"]
        return out
    finally:
        controller.close()
        router.close()


def run_adapt_failure_arm(cfg, tok, model, src, tgt, ckpt, tmpdir,
                          logger=None, recorder=None,
                          capture=None) -> dict:
    """Forced canary failure (chaos ``adapt.canary_fail``): the
    candidate is discarded — ZERO publishes — retries honor exponential
    backoff, and the retry budget exhausts into a permanent
    ``adapt_exhausted`` CRITICAL + tenant quarantine."""
    from induction_network_on_fewrel_tpu.obs import DriftDetector
    from induction_network_on_fewrel_tpu.obs.adapt import (
        EXHAUSTED,
        TRIGGERED,
    )
    from induction_network_on_fewrel_tpu.obs.chaos import (
        ChaosRegistry,
        install,
    )
    from induction_network_on_fewrel_tpu.serving.engine import (
        InferenceEngine,
    )
    from induction_network_on_fewrel_tpu.serving.registry import load_params

    tenant = "tenant0"
    RETRIES, BACKOFF = 2, 30.0
    dknobs = ADAPT_WORLD["drift"]
    drift = DriftDetector(
        eval_interval_s=0.0, logger=logger, recorder=recorder,
        capture=capture, **dknobs,
    )
    engine = InferenceEngine(
        model, load_params(ckpt), cfg, tok, k=cfg.k, buckets=(1, 2, 4),
        logger=logger, drift=drift,
    )
    chaos = ChaosRegistry.parse(
        f"adapt.canary_fail@0*{RETRIES}:{tenant}", logger=logger
    )
    chaos.install()
    out: dict = {"retry_budget": RETRIES, "backoff_s": BACKOFF}
    # Tiny fine-tunes: the chaos point fails the canary regardless, so
    # the arm drills the RETRY/backoff/exhaustion machinery, not model
    # quality.
    controller, work = _build_adapt_controller(
        model, cfg, tok, src, tgt, ckpt, drift,
        engine.publish_checkpoint,
        lambda t, reason="": engine.quarantine_tenant(t, reason=reason),
        tmpdir, steps=8, logger=logger, recorder=recorder,
        capture=capture, retry_budget=RETRIES, backoff_s=BACKOFF,
        verify_window_s=60.0, wall_budget_s=60.0,
    )
    try:
        engine.register_dataset(src, tenant=tenant)
        engine.warmup()
        src_pool, tgt_pool = _adapt_pools(src, tgt, cfg.k)

        def classify(inst):
            return engine.classify(inst, tenant=tenant)

        n_base = dknobs["baseline_n"] + dknobs["min_count"] + 8
        _drive_until(classify, src_pool, stop=lambda: False, cap=n_base)
        _drive_until(
            classify, tgt_pool,
            stop=lambda: controller.state_of(tenant) == TRIGGERED,
            cap=2 * dknobs["window"],
        )
        out["tripped"] = controller.state_of(tenant) == TRIGGERED
        if not out["tripped"]:
            return out
        pv0 = engine.registry.params_version
        swaps0 = engine.stats.snapshot()["swaps"]

        # Attempt 1: train runs (tiny), canary chaos-fails, candidate
        # discarded, backoff scheduled.
        now = 1000.0
        out["attempt1_ran"] = controller.run_once(now=now) == tenant
        info = controller.loop_info(tenant)
        out["attempt1_failed"] = (
            info["state"] == TRIGGERED and info["attempts"] == 1
        )
        # Backoff honored: a retry before not_before does NOT run.
        out["backoff_honored"] = (
            controller.run_once(now=now + 0.5 * BACKOFF) is None
        )
        # Attempt 2 (past the backoff): chaos-fails again -> the retry
        # budget is burned -> EXHAUSTED + quarantine, permanently.
        out["attempt2_ran"] = (
            controller.run_once(now=now + BACKOFF + 1.0) == tenant
        )
        out["exhausted"] = controller.state_of(tenant) == EXHAUSTED
        exhausted_events = [
            e for e in controller.events if e.event == "adapt_exhausted"
        ]
        out["exhausted_criticals"] = len(exhausted_events)
        out["quarantined"] = engine.registry.snapshot(tenant).degraded
        # Permanent: another trigger is absorbed, nothing runs.
        out["retrigger_absorbed"] = not controller.trigger(
            tenant, now=now + 500.0
        )
        out["candidates_cleaned"] = not any(
            p.startswith("cand_") for p in os.listdir(work)
        )
        snap = engine.stats.snapshot()
        out["unexpected_publishes"] = (
            engine.registry.params_version - pv0
            + snap["swaps"] - swaps0
        )
        out["canary_fail_records"] = sum(
            1 for r in controller.records
            if r["action"] == "canary" and r.get("passed") == 0.0
        )
        out["injected"] = len(chaos.fired_log)
        return out
    finally:
        controller.close()
        install(None)
        engine.close()


def adapt_tier1_drill(seed: int = 1, logger=None, recorder=None,
                      capture=None) -> dict:
    """Both arms of the ISSUE 14 drill in one world (what
    tests/test_adapt.py gates in tier-1 and --adapt_drill stamps into
    ADAPT_r*.json). Deterministic under a fixed seed on a fixed stack
    (wall times excepted)."""
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="adapt_drill_") as tmpdir:
        cfg, tok, model, src, tgt, ckpt = _adapt_world(seed, tmpdir)
        out = {
            "seed": seed,
            "config": dict(ADAPT_WORLD["cfg"]),
            "world": {
                k: ADAPT_WORLD[k] for k in
                ("num_relations", "instances_per_relation",
                 "train_iters", "finetune_steps", "canary_floors")
            },
            "success": run_adapt_success_arm(
                cfg, tok, model, src, tgt, ckpt, tmpdir,
                logger=logger, recorder=recorder, capture=capture,
            ),
            "canary_failure": run_adapt_failure_arm(
                cfg, tok, model, src, tgt, ckpt, tmpdir,
                logger=logger, recorder=recorder, capture=capture,
            ),
        }
        out["wall_s"] = round(time.monotonic() - t0, 1)
        out["passed"] = check_adapt_drill(out)
        return out


def check_adapt_drill(drill: dict) -> bool:
    """The drill's acceptance: detect -> adapt -> gate -> publish ->
    verify on the success arm; discard -> backoff -> exhaust -> contain
    on the failure arm."""
    s = drill.get("success", {})
    f = drill.get("canary_failure", {})
    return bool(
        s.get("baseline_armed")
        and s.get("tripped")
        and s.get("canary_passed")
        and s.get("published")
        and s.get("versions_uniform")
        and s.get("dropped_during_publish") == 0
        and s.get("steady_recompiles") == 0
        and s.get("rearmed")
        and s.get("verified")
        # The quality story in numbers: healthy ~0, collapsed high,
        # recovered back under the healthy+band bar.
        and s.get("nota_shifted", 0) >= 0.5
        and abs(s.get("nota_post", 1.0) - s.get("nota_healthy", 0.0))
        <= max(s.get("nota_band") or 0.05, 0.05) + 1e-9
        and f.get("tripped")
        and f.get("attempt1_failed")
        and f.get("backoff_honored")
        and f.get("exhausted")
        and f.get("exhausted_criticals") == 1
        and f.get("quarantined")
        and f.get("retrigger_absorbed")
        and f.get("candidates_cleaned")
        and f.get("unexpected_publishes") == 0
        and f.get("canary_fail_records") == f.get("retry_budget")
    )


# --- fleet soak (ISSUE 13) --------------------------------------------------


def _fleet_datasets(args, count: int) -> list:
    """``count`` distinct synthetic relation corpora. Tenants cycle over
    them: distinct-enough supports for a real multi-tenant workload,
    while the registry's digest dedup keeps the distill cost bounded at
    1k/10k-tenant scale (CPU-honest — the per-tenant snapshots, routing,
    and placement work are all still per-tenant)."""
    from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel

    return [
        make_synthetic_fewrel(
            num_relations=args.N, instances_per_relation=args.K + 10,
            vocab_size=2000, seed=args.seed + 101 * d,
        )
        for d in range(count)
    ]


def _run_fleet_closed(router, pools, tenant_names, concurrency, duration,
                      seed, deadline_s=10.0):
    """Closed-loop workers striding across ``tenant_names`` through the
    ROUTER. Returns aggregate latency percentiles + the three outcome
    counters the fleet invariants gate on: ``shed`` (fleet-share or
    replica backpressure — back off and retry, same discipline as
    run_closed), ``degraded`` (failover NOTA verdicts — answers, not
    errors), ``errors`` (everything else — the dropped_during_failover
    zero-band)."""
    import numpy as np

    from induction_network_on_fewrel_tpu.serving.batcher import Saturated

    lat: list[float] = []
    counters = {"shed": 0, "degraded": 0, "errors": 0}
    lock = threading.Lock()
    stop = time.monotonic() + duration

    def worker(wi: int):
        r = np.random.default_rng(seed + wi)
        mine, me = [], {"shed": 0, "degraded": 0, "errors": 0}
        i = wi
        while time.monotonic() < stop:
            tenant = tenant_names[i % len(tenant_names)]
            i += concurrency
            pool = pools[tenant]
            inst = pool[int(r.integers(len(pool)))]
            t0 = time.monotonic()
            try:
                v = router.classify(inst, deadline_s, tenant=tenant)
                mine.append(time.monotonic() - t0)
                if v.get("degraded"):
                    me["degraded"] += 1
            except Saturated as e:
                me["shed"] += 1
                delay = e.retry_after_s * (0.75 + 0.5 * float(r.random()))
                time.sleep(max(0.0, min(delay, stop - time.monotonic())))
            except Exception:  # noqa: BLE001 — counted: the zero-band
                me["errors"] += 1
        with lock:
            lat.extend(mine)
            for k in counters:
                counters[k] += me[k]

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(concurrency)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    return {
        "served": len(lat),
        "qps": round(len(lat) / wall, 1),
        "p50_ms": pct_ms(lat, 50),
        "p99_ms": pct_ms(lat, 99),
        "wall": wall,
        **counters,
    }


def run_fleet_soak(args, ckpt, logger, recorder, capture) -> dict:
    """The ISSUE 13 fleet soak: R in-process replicas behind the router,
    T tenants rendezvous-placed across them, then:

    1. onboarding — T tenants registered through the control plane
       (owners recorded, placement re-resolution consistent);
    2. mixed closed-loop traffic with ONE all-or-nothing fan-out publish
       fired mid-load from a side thread: zero dropped requests, zero
       steady-state recompiles on every replica, params_version uniform;
    3. replica add — placement churn measured against the rendezvous
       bound (~1/(R+1)), displaced tenants re-registered and re-served;
    4. ``fleet.replica_kill`` drill — an injected replica death mid-
       traffic: failover serves degraded NOTA (zero drops), the
       watchdog latches ONE replica_dead CRITICAL, re-placement
       recovers the tenants, and a revive re-arms the latch.
    """
    from collections import Counter

    from induction_network_on_fewrel_tpu.fleet import (
        FleetControl,
        FleetPlacement,
        FleetRouter,
        InProcessReplica,
    )
    from induction_network_on_fewrel_tpu.obs import HealthWatchdog
    from induction_network_on_fewrel_tpu.obs.chaos import (
        ChaosRegistry,
        install,
    )
    from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker

    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    R, T = args.fleet, max(args.tenants, 1)
    # The kill drill's criticals flow through logger HOOKS (watchdog):
    # with no run dir, a pathless logger still carries the record stream.
    own_logger = logger is None
    if own_logger:
        logger = MetricsLogger(None, quiet=True)
    watchdog = HealthWatchdog(
        logger=logger, recorder=recorder, capture=capture
    )
    logger.add_hook(watchdog.observe_record)

    def mk():
        return build_engine(args, ckpt, "continuous", logger=logger)

    replicas = {
        f"r{i:02d}": InProcessReplica(f"r{i:02d}", mk()) for i in range(R)
    }
    router = FleetRouter(
        replicas, logger=logger,
        breaker=CircuitBreaker(failure_threshold=3, open_s=1.0),
        queue_capacity_per_replica=args.queue_depth,
    )
    control = FleetControl(router)
    out: dict = {"replicas": R, "tenants": T}
    try:
        # 1. onboarding.
        datasets = _fleet_datasets(args, min(8, T))
        names = [f"t{i:04d}" for i in range(T)]
        t0 = time.monotonic()
        for i, tenant in enumerate(names):
            control.register_tenant(tenant, datasets[i % len(datasets)])
        out["register_s"] = round(time.monotonic() - t0, 3)
        out["warmup_compiles"] = sum(
            h.warmup() for h in router.replicas.values()
        )
        dist = Counter(e.owner for e in router.directory.values())
        out["placement_distribution"] = dict(sorted(dist.items()))
        owners = router.placement.owners(names)
        out["placement_consistent"] = all(
            owners[t] == router.directory[t].owner for t in names
        )
        pools = {
            t: [
                inst
                for rel in datasets[i % len(datasets)].rel_names
                for inst in datasets[i % len(datasets)].instances[rel][args.K:]
            ]
            for i, t in enumerate(names)
        }

        # 2. mixed traffic + mid-load fan-out publish.
        served0 = {
            rid: h.stats_snapshot()["served"]
            for rid, h in router.replicas.items()
        }
        pub: dict = {}

        def _publish():
            p0 = time.monotonic()
            try:
                pub["params_version"] = control.publish_params(
                    router.replicas[sorted(router.replicas)[0]].engine.params
                )
            except Exception as e:  # noqa: BLE001 — report, never die
                pub["error"] = repr(e)
            pub["publish_s"] = round(time.monotonic() - p0, 4)

        # Per-window, per-replica occupancy/shed time series (ISSUE 16
        # satellite): the autoscaler A/B and the elastic-drill verdict
        # need the TRAJECTORY through the load, not just endpoint
        # aggregates — the sampler rides the traffic phase on a side
        # thread and lands in the artifact.
        ts_windows: list = []
        ts_window_s = max(args.duration / 8.0, 0.25)
        ts_stop = threading.Event()

        def _sample_timeseries():
            w = 0
            last_shed = router.snapshot()["shed"]
            while not ts_stop.wait(ts_window_s):
                snap = router.snapshot()
                row = {
                    "window": w,
                    "t_s": round((w + 1) * ts_window_s, 3),
                    "shed_delta": snap["shed"] - last_shed,
                    "inflight": snap["inflight"],
                    "replicas": {},
                }
                last_shed = snap["shed"]
                for rid in sorted(router.replicas):
                    try:
                        s = router.replicas[rid].stats_snapshot()
                    except Exception:  # noqa: BLE001 — dead mid-drill
                        continue
                    row["replicas"][rid] = {
                        "occupancy": s["batch_occupancy"],
                        "queue_depth": s["queue_depth"],
                        "served": s["served"],
                    }
                ts_windows.append(row)
                w += 1

        sampler = threading.Thread(target=_sample_timeseries, daemon=True)
        sampler.start()
        timer = threading.Timer(max(args.duration / 2, 0.5), _publish)
        timer.start()
        traffic = _run_fleet_closed(
            router, pools, names, args.concurrency, args.duration,
            args.seed,
        )
        timer.join(timeout=120.0)
        ts_stop.set()
        sampler.join(timeout=10.0)
        out["timeseries"] = {
            "window_s": ts_window_s, "windows": ts_windows,
        }
        wall = traffic.pop("wall")
        out["traffic"] = traffic
        per_replica = {}
        for rid, h in sorted(router.replicas.items()):
            s = h.stats_snapshot()
            per_replica[rid] = {
                "qps": round((s["served"] - served0[rid]) / wall, 1),
                "served": s["served"],
                "p50_ms": s["p50_ms"],
                "p99_ms": s["p99_ms"],
                "occupancy": s["batch_occupancy"],
                "steady_recompiles": s["steady_recompiles"],
            }
        out["per_replica"] = per_replica
        versions = {
            rid: h.params_version for rid, h in router.replicas.items()
        }
        out["fanout_publish"] = {
            **pub,
            "replicas": len(versions),
            "uniform": len(set(versions.values())) == 1,
            "dropped": traffic["errors"],
            "steady_recompiles": sum(
                r["steady_recompiles"] for r in per_replica.values()
            ),
        }

        # 3. replica add: churn against the rendezvous bound.
        before = router.placement.owners(names)
        new_rid = f"r{R:02d}"
        control.add_replica(InProcessReplica(new_rid, mk()))
        after = router.placement.owners(names)
        moved = FleetPlacement.churn(before, after)
        replaced = control.replace_tenants()
        router.replicas[new_rid].warmup()
        moved_tenants = [t for t in names if after[t] != before[t]]
        out["placement"] = {
            "tenants": T,
            "replicas": R,
            "add_churn_frac": round(moved / T, 4),
            # 1/(R+1) expectation + slack — the bound tests pin.
            "add_churn_bound": round(1.5 / (R + 1), 4),
            # The 1.5x slack is a LARGE-T concentration bound: churn is
            # binomial with mean T/(R+1), and a handful of tenants can
            # legitimately all move. Gate only in the statistical
            # regime; tiny fleets record the number unbanded.
            "churn_ok": T < 100 or moved / T <= 1.5 / (R + 1),
            "moved": moved,
            "replaced": replaced,
            # Vacuously true when nothing moved (legitimate at tiny T).
            "moved_tenants_served": all(
                not router.classify(
                    pools[t][0], 10.0, tenant=t
                ).get("degraded")
                for t in moved_tenants[:5]
            ),
        }

        # 4. replica-kill failover drill.
        victim = router.directory[names[0]].owner
        affected = [
            t for t, e in router.directory.items() if e.owner == victim
        ]
        install(ChaosRegistry.parse(
            f"fleet.replica_kill@0:{victim}", logger=logger
        ))
        kill_traffic = _run_fleet_closed(
            router, pools, names[: min(T, 128)], 2,
            max(1.5, args.duration / 3), args.seed + 7,
        )
        install(None)
        crits = [e for e in watchdog.events if e.event == "replica_dead"]
        replaced_kill = control.replace_tenants()
        recovered = all(
            not router.classify(pools[t][0], 10.0, tenant=t).get("degraded")
            for t in affected[:5]
        )
        router.revive_replica(victim, reason="drill recovery")
        latch_rearmed = (
            f"replica_dead:{victim}" not in watchdog._latched
        )
        moved_back = control.replace_tenants()
        out["replica_kill"] = {
            "victim": victim,
            "affected_tenants": len(affected),
            "degraded_served": kill_traffic["degraded"],
            "dropped_during_failover": kill_traffic["errors"],
            "criticals": len(crits),
            "once_latched": len(crits) == 1,
            "replaced": replaced_kill,
            "recovered": recovered,
            "latch_rearmed_on_revive": latch_rearmed,
            "moved_back_on_revive": moved_back,
        }
        router.emit_stats()
        final_recompiles = sum(
            h.stats_snapshot()["steady_recompiles"]
            for h in router.replicas.values()
        )
        out["zero_bands"] = {
            "dropped_during_failover": kill_traffic["errors"],
            "steady_recompiles": final_recompiles,
        }
        out["passed"] = check_fleet_soak(out)
        return out
    finally:
        install(None)
        router.close()
        # Unhook the soak's watchdog: a later drill on the SAME logger
        # (the tier-1 miniature in main's fleet branch) must not emit
        # every fault critical twice.
        if watchdog.observe_record in logger.hooks:
            logger.hooks.remove(watchdog.observe_record)
        if own_logger:
            logger.close()


def check_fleet_soak(out: dict) -> bool:
    """The soak's acceptance: consistent placement, an atomic fan-out
    publish under load (uniform version, zero drops, zero recompiles),
    bounded add-churn with displaced tenants re-served, and the kill
    drill's full inject -> degrade -> re-place -> recover arc."""
    fp = out.get("fanout_publish", {})
    pl = out.get("placement", {})
    rk = out.get("replica_kill", {})
    zb = out.get("zero_bands", {})
    return bool(
        out.get("placement_consistent")
        and fp.get("params_version") is not None
        and fp.get("uniform")
        and fp.get("dropped") == 0
        and fp.get("steady_recompiles") == 0
        and isinstance(pl.get("add_churn_frac"), float)
        and pl.get("churn_ok")
        and pl.get("moved_tenants_served")
        and rk.get("degraded_served", 0) >= 1
        and rk.get("criticals") == 1
        and rk.get("once_latched")
        and rk.get("recovered")
        and rk.get("latch_rearmed_on_revive")
        and rk.get("dropped_during_failover") == 0
        and zb.get("steady_recompiles") == 0
    )


def fleet_tier1_drill(seed: int = 0, logger=None) -> dict:
    """The miniature 3-replica fleet leg the tier-1 gate replays
    (tests/test_fleet.py — the tests/test_scenarios.py artifact
    discipline): a tiny self-contained world, every fleet invariant in
    one pass. Deterministic in ``seed``: the placement numbers are pure
    functions of the tenant/replica ids, so the committed FLEET artifact
    can pin them EXACTLY and a hash/placement change fails tier-1 until
    the artifact is re-emitted."""
    import jax
    from collections import Counter

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
    from induction_network_on_fewrel_tpu.fleet import (
        FleetControl,
        FleetPlacement,
        FleetPublishError,
        FleetRouter,
        InProcessReplica,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.obs import HealthWatchdog
    from induction_network_on_fewrel_tpu.obs.chaos import (
        ChaosRegistry,
        install,
    )
    from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    R, T = 3, 48
    cfg = ExperimentConfig(
        model="induction", encoder="cnn", hidden_size=16,
        vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
        induction_dim=8, ntn_slices=4, routing_iters=2,
        n=3, train_n=3, k=2, q=2, device="cpu", seed=seed,
    )
    vocab = make_synthetic_glove(
        vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(seed),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    # A hook-bearing logger even with no run dir: the watchdog's latch
    # assertions need the record stream, not the jsonl file.
    own_logger = logger if logger is not None else MetricsLogger(
        None, quiet=True
    )
    watchdog = HealthWatchdog(logger=own_logger)
    own_logger.add_hook(watchdog.observe_record)

    def mk():
        return InferenceEngine(
            model, params, cfg, tok, k=cfg.k, buckets=(1, 2, 4),
            logger=own_logger,
        )

    replicas = {
        f"r{i:02d}": InProcessReplica(f"r{i:02d}", mk()) for i in range(R)
    }
    router = FleetRouter(
        replicas, logger=own_logger,
        breaker=CircuitBreaker(failure_threshold=3, open_s=1.0),
        queue_capacity_per_replica=64,
    )
    control = FleetControl(router)
    out: dict = {"replicas": R, "tenants": T, "seed": seed}
    try:
        datasets = [
            make_synthetic_fewrel(
                num_relations=cfg.n, instances_per_relation=cfg.k + 6,
                vocab_size=cfg.vocab_size - 2, seed=seed + 101 * d,
            )
            for d in range(4)
        ]
        names = [f"t{i:02d}" for i in range(T)]
        for i, tenant in enumerate(names):
            control.register_tenant(tenant, datasets[i % 4])
        for h in router.replicas.values():
            h.warmup()
        dist = Counter(e.owner for e in router.directory.values())
        out["placement_distribution"] = dict(sorted(dist.items()))
        owners = router.placement.owners(names)
        out["placement_consistent"] = all(
            owners[t] == router.directory[t].owner for t in names
        )
        pools = {
            t: [
                inst for rel in datasets[i % 4].rel_names
                for inst in datasets[i % 4].instances[rel][cfg.k:]
            ]
            for i, t in enumerate(names)
        }
        # Mixed traffic: one verdict per tenant through the router.
        verdicts = [
            router.classify(pools[t][0], 10.0, tenant=t) for t in names
        ]
        out["traffic_ok"] = all(
            v["tenant"] == t and not v.get("degraded")
            for v, t in zip(verdicts, names)
        )

        # Poisoned fan-out: the MIDDLE replica's prepare is injected
        # (publish.nan_params@1) — atomicity means the whole fleet rolls
        # back with in-flight batches untouched.
        versions0 = {
            rid: h.params_version for rid, h in router.replicas.items()
        }
        futs = [
            router.submit(pools[t][1], 10.0, tenant=t) for t in names[:8]
        ]
        install(ChaosRegistry.parse("publish.nan_params@1",
                                    logger=own_logger))
        try:
            control.publish_params(params)
            rolled_back = False
        except FleetPublishError:
            rolled_back = True
        install(None)
        inflight_ok = all(
            "label" in f.result(timeout=30.0) for f in futs
        )
        out["poisoned_fanout"] = {
            "rolled_back": rolled_back,
            "versions_unchanged": versions0 == {
                rid: h.params_version
                for rid, h in router.replicas.items()
            },
            "inflight_untouched": inflight_ok,
        }
        # Clean fan-out commits uniformly.
        version = control.publish_params(params)
        out["fanout_publish"] = {
            "params_version": version,
            "uniform": len({
                h.params_version for h in router.replicas.values()
            }) == 1,
        }

        # Replica add: churn at the rendezvous bound.
        before = router.placement.owners(names)
        control.add_replica(InProcessReplica(f"r{R:02d}", mk()))
        after = router.placement.owners(names)
        moved = FleetPlacement.churn(before, after)
        control.replace_tenants()
        router.replicas[f"r{R:02d}"].warmup()
        out["add_churn_frac"] = round(moved / T, 4)
        out["add_churn_bound"] = round(1.5 / (R + 1), 4)

        # Replica-kill failover: degraded -> re-place -> recover.
        victim = router.directory[names[0]].owner
        install(ChaosRegistry.parse(f"fleet.replica_kill@0:{victim}",
                                    logger=own_logger))
        v_deg = router.classify(pools[names[0]][0], 10.0, tenant=names[0])
        install(None)
        crits = [e for e in watchdog.events if e.event == "replica_dead"]
        # Once-latch: more traffic to displaced tenants adds nothing.
        router.classify(pools[names[0]][0], 10.0, tenant=names[0])
        crits2 = [e for e in watchdog.events if e.event == "replica_dead"]
        control.replace_tenants()
        v_rec = router.classify(pools[names[0]][0], 10.0, tenant=names[0])
        router.revive_replica(victim, reason="drill")
        out["replica_kill"] = {
            "victim": victim,
            "degraded_verdict": bool(
                v_deg.get("degraded") and v_deg.get("failover")
            ),
            "criticals": len(crits),
            "once_latched": len(crits2) == 1,
            "recovered": not v_rec.get("degraded"),
            "latch_rearmed_on_revive": (
                f"replica_dead:{victim}" not in watchdog._latched
            ),
        }
        out["steady_recompiles"] = sum(
            h.stats_snapshot()["steady_recompiles"]
            for h in router.replicas.values()
        )
        out["passed"] = bool(
            out["placement_consistent"]
            and out["traffic_ok"]
            and all(out["poisoned_fanout"].values())
            and out["fanout_publish"]["uniform"]
            and out["add_churn_frac"] <= out["add_churn_bound"]
            and out["replica_kill"]["degraded_verdict"]
            and out["replica_kill"]["criticals"] == 1
            and out["replica_kill"]["once_latched"]
            and out["replica_kill"]["recovered"]
            and out["replica_kill"]["latch_rearmed_on_revive"]
            and out["steady_recompiles"] == 0
        )
        return out
    finally:
        install(None)
        router.close()
        if watchdog.observe_record in own_logger.hooks:
            own_logger.hooks.remove(watchdog.observe_record)
        if logger is None:
            own_logger.close()


def recovery_tier1_drill(seed: int = 0, logger=None) -> dict:
    """The ISSUE 15 durability drill, miniature + deterministic (the
    fleet_tier1_drill discipline — the committed RECOVERY artifact IS
    the tier-1 replay): one journaled 3-replica fleet, then the three
    recovery arms end to end.

    * **Router kill-9**: every control-plane op write-ahead-logged,
      then the router object is thrown away mid-life (the crash) WITH
      one replica's process replaced by a fresh engine (empty registry,
      params_version 0 — the host that also died). A fresh router's
      ``recover(journal)`` must rebuild the directory BITWISE (owners,
      thresholds, quarantine flags, support digests), keep placement
      identical, re-register + catch the fresh replica up to the
      journaled committed generation, and lose ZERO tenants.
    * **Replica kill -> supervised restart**: the supervisor's first
      restart attempt is made to fail (backoff honored on the injected
      clock — attempt 2 runs only after the deterministic-jitter
      delay), the second succeeds: re-registration, catch-up to the
      uniform params_version, warmup, breaker reset, revive — with
      traffic to the surviving replicas dropping NOTHING during the
      window and zero steady-state recompiles fleet-wide.
    * **Torn journal tail**: the ``journal.torn_write`` chaos point
      tears the WAL mid-record; reopening the journal truncates at the
      tear (action="journal_truncated"), recovers every record before
      it, and the journal accepts appends again.
    """
    import jax
    from collections import Counter

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
    from induction_network_on_fewrel_tpu.fleet import (
        FleetControl,
        FleetJournal,
        FleetRouter,
        InProcessReplica,
        ReplicaSupervisor,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.obs.chaos import (
        ChaosRegistry,
        install,
    )
    from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    R, T = 3, 18
    cfg = ExperimentConfig(
        model="induction", encoder="cnn", hidden_size=16,
        vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
        induction_dim=8, ntn_slices=4, routing_iters=2,
        n=3, train_n=3, k=2, q=2, device="cpu", seed=seed,
    )
    vocab = make_synthetic_glove(
        vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(seed),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    own_logger = logger if logger is not None else MetricsLogger(
        None, quiet=True
    )
    tmp = tempfile.TemporaryDirectory(prefix="recovery_drill_")
    out: dict = {"replicas": R, "tenants": T, "seed": seed}
    routers: list = []
    journals: list = []
    try:
        # The publishable artifact the journaled catch-up re-drives.
        ckpt = os.path.join(tmp.name, "ckpt")
        state0 = init_state(
            model, cfg,
            zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
            zero_batch(cfg.max_length, (1, cfg.total_q)),
            rng=jax.random.key(seed),
        )
        mngr = CheckpointManager(ckpt, cfg, stage="off")
        try:
            mngr.save(0, state0, val_accuracy=0.0)
            mngr.wait()
        finally:
            mngr.close()

        journal = FleetJournal(
            os.path.join(tmp.name, "journal"), fsync="always",
            logger=own_logger,
        )
        journals.append(journal)

        def mk():
            return InferenceEngine(
                model, params, cfg, tok, k=cfg.k, buckets=(1, 2, 4),
                logger=own_logger,
            )

        replicas = {
            f"r{i:02d}": InProcessReplica(f"r{i:02d}", mk())
            for i in range(R)
        }
        router = FleetRouter(
            replicas, logger=own_logger,
            breaker=CircuitBreaker(failure_threshold=3, open_s=1.0),
            queue_capacity_per_replica=64,
        )
        routers.append(router)
        control = FleetControl(router, journal=journal)
        datasets = [
            make_synthetic_fewrel(
                num_relations=cfg.n, instances_per_relation=cfg.k + 6,
                vocab_size=cfg.vocab_size - 2, seed=seed + 101 * d,
            )
            for d in range(4)
        ]
        names = [f"t{i:02d}" for i in range(T)]
        for i, tenant in enumerate(names):
            control.register_tenant(tenant, datasets[i % 4])
            if i % 3 == 0:
                control.set_nota_threshold(tenant, 0.25 + 0.05 * (i % 4))
        for h in router.replicas.values():
            h.warmup()
        pools = {
            t: [
                inst for rel in datasets[i % 4].rel_names
                for inst in datasets[i % 4].instances[rel][cfg.k:]
            ]
            for i, t in enumerate(names)
        }
        # The journaled publish every catch-up re-drives (version 1
        # fleet-wide, ckpt path recorded).
        control.publish_checkpoint(ckpt)
        # Quarantine AFTER the publish (journal order matters: a
        # committed publish clears engine-level quarantine by design,
        # so recovery must re-assert flags journaled after it — the
        # exact replay-order case the drill proves).
        control.quarantine_tenant(names[1], reason="drill: operator hold")
        dir_before = router.directory_view()
        placement_before = router.placement.owners(names)
        out["placement_distribution"] = dict(sorted(Counter(
            e.owner for e in router.directory.values()
        ).items()))
        out["journal_records_at_kill"] = journal.seq

        # --- ARM A: router kill-9 + one replica host lost -----------------
        # Mid-traffic: these futures are IN FLIGHT when the router
        # dies. The replicas own the queued work, so they must resolve
        # normally even though the router object that admitted them is
        # gone (zero drops from the crash itself).
        lost_rid = sorted(replicas)[1]
        survivors_of_lost = [
            t for t, e in router.directory.items() if e.owner != lost_rid
        ]
        inflight = [
            router.submit(pools[t][1], 10.0, tenant=t)
            for t in survivors_of_lost[:6]
        ]
        replicas[lost_rid].close()   # that host died WITH the router
        replicas2 = dict(replicas)
        replicas2[lost_rid] = InProcessReplica(lost_rid, mk())
        # The "restarted" router process: fresh object, fresh breaker,
        # nothing carried over but the journal directory on disk.
        journal2 = FleetJournal(
            os.path.join(tmp.name, "journal"), fsync="always",
            logger=own_logger,
        )
        journals.append(journal2)
        router2 = FleetRouter(
            replicas2, logger=own_logger,
            breaker=CircuitBreaker(failure_threshold=3, open_s=1.0),
            queue_capacity_per_replica=64,
        )
        routers.append(router2)
        control2 = FleetControl(router2, journal=journal2)
        summary = router2.recover(journal2)
        dir_after = router2.directory_view()
        inflight_survived = all(
            "label" in f.result(timeout=30.0) for f in inflight
        )
        served = degraded = errors = 0
        for t in names:
            try:
                v = router2.classify(pools[t][0], 10.0, tenant=t)
                served += 1
                degraded += bool(v.get("degraded"))
            except Exception:  # noqa: BLE001 — counted: the zero-band
                errors += 1
        versions = {
            rid: h.params_version for rid, h in router2.replicas.items()
        }
        out["router_kill"] = {
            "lost_replica": lost_rid,
            "directory_bitwise": dir_after == dir_before,
            "placement_identical":
                router2.placement.owners(names) == placement_before,
            "tenants_lost": T - len(router2.directory),
            "reregistered": summary["reregistered"],
            "caught_up": summary["caught_up"],
            "params_version_uniform": len(set(versions.values())) == 1,
            "params_version": max(versions.values()),
            "inflight_at_kill": len(inflight),
            "inflight_survived": inflight_survived,
            "served": served,
            # names[1] is the operator-quarantined tenant: its degraded
            # verdict PROVES the flag survived the crash.
            "quarantine_survived": degraded == 1,
            "errors": errors,
        }

        # --- ARM B: replica kill -> supervised restart --------------------
        clock = {"t": 0.0}
        attempts = {"n": 0}

        def restart_fn(rid):
            attempts["n"] += 1
            if attempts["n"] == 1:
                raise RuntimeError("injected spawn failure (drill)")
            return InProcessReplica(rid, mk())

        sup = ReplicaSupervisor(
            router2, restart_fn, journal=journal2,
            backoff_s=0.5, restart_budget=3,
            clock=lambda: clock["t"], logger=own_logger,
        )
        victim = router2.directory[names[0]].owner
        victim_tenants = [
            t for t, e in router2.directory.items() if e.owner == victim
        ]
        router2.replicas[victim].close()
        router2.mark_replica_dead(victim, reason="drill kill")
        # Traffic to the SURVIVORS while the victim is down + restarting:
        # the dropped_during_catchup zero-band.
        survivors = [t for t in names if t not in victim_tenants
                     and t != names[1]]
        catchup_errors = 0
        for t in survivors:
            try:
                router2.classify(pools[t][0], 10.0, tenant=t)
            except Exception:  # noqa: BLE001 — counted: the zero-band
                catchup_errors += 1
        p1 = sup.poll()                      # attempt 1: injected failure
        delay = sup.next_delay(victim, 1)
        clock["t"] = delay * 0.5
        p2 = sup.poll()                      # inside backoff: must not try
        clock["t"] = delay + 1e-6
        p3 = sup.poll()                      # attempt 2: succeeds
        for t in survivors:
            try:
                router2.classify(pools[t][0], 10.0, tenant=t)
            except Exception:  # noqa: BLE001 — counted: the zero-band
                catchup_errors += 1
        recovered = all(
            not router2.classify(
                pools[t][0], 10.0, tenant=t
            ).get("degraded")
            for t in victim_tenants[:4] if t != names[1]
        )
        versions = {
            rid: h.params_version for rid, h in router2.replicas.items()
        }
        steady = sum(
            h.stats_snapshot()["steady_recompiles"]
            for h in router2.replicas.values()
        )
        out["replica_kill"] = {
            "victim": victim,
            "affected_tenants": len(victim_tenants),
            "restart_attempts": attempts["n"],
            "backoff_honored": (
                p1["failed"] == [victim] and p2["restarted"] == []
                and p2["failed"] == [] and p3["restarted"] == [victim]
            ),
            "caught_up_version": max(versions.values()),
            "params_version_uniform": len(set(versions.values())) == 1,
            "recovered": recovered,
            "dropped_during_catchup": catchup_errors,
            "steady_recompiles": steady,
        }

        # --- ARM C: torn journal tail -------------------------------------
        state_before_tear = json.dumps(
            journal2.materialize().to_dict(), sort_keys=True
        )
        install(ChaosRegistry.parse("journal.torn_write@0",
                                    logger=own_logger))
        control2.set_nota_threshold(names[2], 0.5)   # the torn append
        install(None)
        torn_refused = False
        try:
            control2.set_nota_threshold(names[3], 0.5)
        except Exception:  # noqa: BLE001 — the journal must refuse
            torn_refused = True
        journal3 = FleetJournal(
            os.path.join(tmp.name, "journal"), fsync="always",
            logger=own_logger,
        )
        state_after_heal = json.dumps(
            journal3.materialize().to_dict(), sort_keys=True
        )
        # Healed: appends land again and replay picks them up.
        journal3.append("tenant_threshold", tenant=names[2], threshold=0.5)
        out["torn_tail"] = {
            "append_refused_after_tear": torn_refused,
            "prefix_recovered": state_after_heal == state_before_tear,
            "appendable_after_heal":
                journal3.materialize().tenants[names[2]]["nota_threshold"]
                == 0.5,
        }
        journal3.close()

        out["zero_bands"] = {
            "tenants_lost": out["router_kill"]["tenants_lost"],
            "steady_recompiles": out["replica_kill"]["steady_recompiles"],
            "dropped_during_catchup":
                out["replica_kill"]["dropped_during_catchup"],
        }
        out["passed"] = check_recovery_drill(out)
        return out
    finally:
        install(None)
        for r in routers:
            r.close()
        for j in journals:
            j.close()
        if logger is None:
            own_logger.close()
        tmp.cleanup()


def check_recovery_drill(out: dict) -> bool:
    """The drill's acceptance: bitwise directory + identical placement
    + zero tenant loss after the router kill, supervised restart with
    honored backoff catching the replica up to the uniform generation
    with zero drops and zero steady recompiles, and the torn tail
    recovering its full clean prefix."""
    rk = out.get("router_kill", {})
    rep = out.get("replica_kill", {})
    tt = out.get("torn_tail", {})
    zb = out.get("zero_bands", {})
    return bool(
        rk.get("directory_bitwise")
        and rk.get("placement_identical")
        and rk.get("tenants_lost") == 0
        and rk.get("reregistered", 0) >= 1
        and rk.get("caught_up", 0) >= 1
        and rk.get("params_version_uniform")
        and rk.get("quarantine_survived")
        and rk.get("inflight_survived")
        and rk.get("errors") == 0
        and rep.get("backoff_honored")
        and rep.get("params_version_uniform")
        and rep.get("recovered")
        and rep.get("dropped_during_catchup") == 0
        and rep.get("steady_recompiles") == 0
        and tt.get("append_refused_after_tear")
        and tt.get("prefix_recovered")
        and tt.get("appendable_after_heal")
        and zb.get("tenants_lost") == 0
        and zb.get("steady_recompiles") == 0
        and zb.get("dropped_during_catchup") == 0
    )


def elastic_tier1_drill(seed: int = 0, logger=None) -> dict:
    """The ISSUE 16 elasticity drill, miniature + deterministic (the
    committed ELASTIC artifact IS the tier-1 replay): one journaled
    single-replica fleet with a hot standby tailing the WAL, then the
    full diurnal cycle end to end.

    * **Ramp -> scale-out**: two consecutive pressure readings on the
      autoscaler's injected clock (the SENSOR is scripted, like chaos
      injection; the scale MECHANICS are real) spawn a fresh replica,
      catch it up to the journaled committed params_version, pre-warm
      exactly the tenants the rendezvous will hand it, and only then
      join placement — traffic through the scale event drops nothing
      and the newcomer's first queries hit compiled programs (zero
      steady recompiles THROUGH the scale event).
    * **Trough -> drain-in**: idle readings drain the LIFO victim with
      requests still queued on it — the policy waits for an EMPTY
      queue before ``replace_tenants`` moves the registrations and
      ``replica_retire`` removes it, so every in-flight future
      resolves with a real verdict (drain-in never drops).
    * **Second ramp -> router kill-9 -> standby promotion**: the
      primary router object is thrown away mid-ramp; the standby
      serves known tenants degraded-NOTA (never dropped) until
      ``promote()`` — lease first (the zombie primary's next journal
      append raises instead of split-braining the log), final
      catch-up replay, then a recover() that rebuilds the directory
      BITWISE with identical placement and zero tenants lost.
    """
    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
    from induction_network_on_fewrel_tpu.fleet import (
        FleetAutoscaler,
        FleetControl,
        FleetJournal,
        FleetRouter,
        HotStandby,
        InProcessReplica,
        JournalError,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.serving.breaker import CircuitBreaker
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    T = 12
    cfg = ExperimentConfig(
        model="induction", encoder="cnn", hidden_size=16,
        vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
        induction_dim=8, ntn_slices=4, routing_iters=2,
        n=3, train_n=3, k=2, q=2, device="cpu", seed=seed,
    )
    vocab = make_synthetic_glove(
        vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(seed),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    own_logger = logger if logger is not None else MetricsLogger(
        None, quiet=True
    )
    tmp = tempfile.TemporaryDirectory(prefix="elastic_drill_")
    out: dict = {"replicas_start": 1, "tenants": T, "seed": seed}
    routers: list = []
    journals: list = []
    handles: dict = {}
    standby = None
    try:
        ckpt = os.path.join(tmp.name, "ckpt")
        state0 = init_state(
            model, cfg,
            zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
            zero_batch(cfg.max_length, (1, cfg.total_q)),
            rng=jax.random.key(seed),
        )
        mngr = CheckpointManager(ckpt, cfg, stage="off")
        try:
            mngr.save(0, state0, val_accuracy=0.0)
            mngr.wait()
        finally:
            mngr.close()

        jdir = os.path.join(tmp.name, "journal")
        journal = FleetJournal(jdir, fsync="always", logger=own_logger)
        journals.append(journal)
        journal.acquire_lease("primary")   # the single-writer latch

        def mk():
            return InferenceEngine(
                model, params, cfg, tok, k=cfg.k, buckets=(1, 2, 4),
                logger=own_logger,
            )

        def spawn(rid):
            handles[rid] = InProcessReplica(rid, mk())
            return handles[rid]

        handles["r00"] = InProcessReplica("r00", mk())
        router = FleetRouter(
            {"r00": handles["r00"]}, logger=own_logger,
            breaker=CircuitBreaker(failure_threshold=3, open_s=1.0),
            queue_capacity_per_replica=64,
        )
        routers.append(router)
        control = FleetControl(router, journal=journal)
        datasets = [
            make_synthetic_fewrel(
                num_relations=cfg.n, instances_per_relation=cfg.k + 6,
                vocab_size=cfg.vocab_size - 2, seed=seed + 101 * d,
            )
            for d in range(4)
        ]
        names = [f"t{i:02d}" for i in range(T)]
        for i, tenant in enumerate(names):
            control.register_tenant(tenant, datasets[i % 4])
            if i % 3 == 0:
                control.set_nota_threshold(tenant, 0.25 + 0.05 * (i % 4))
        handles["r00"].warmup()
        pools = {
            t: [
                inst for rel in datasets[i % 4].rel_names
                for inst in datasets[i % 4].instances[rel][cfg.k:]
            ]
            for i, t in enumerate(names)
        }
        control.publish_checkpoint(ckpt)   # v1 — what catch-up re-drives
        control.quarantine_tenant(names[1], reason="drill: operator hold")

        # The hot standby arms BEFORE anything interesting happens and
        # tails the same WAL from here on.
        standby = HotStandby(jdir, owner="standby", logger=own_logger)
        standby.poll()

        clockd = {"t": 0.0}
        scaler = FleetAutoscaler(
            control, spawn, min_replicas=1, max_replicas=2,
            high_occupancy=0.75, low_occupancy=0.20,
            high_windows=2, low_windows=2,
            cooldown_s=5.0, scale_budget_s=30.0,
            clock=lambda: clockd["t"], logger=own_logger,
        )

        def serve_all(front) -> tuple:
            served = degraded = errors = 0
            for t in names:
                try:
                    v = front.classify(pools[t][0], 10.0, tenant=t)
                    served += 1
                    degraded += bool(v.get("degraded"))
                except Exception:  # noqa: BLE001 — counted: the zero-band
                    errors += 1
            return served, degraded, errors

        # --- PHASE A: ramp -> scale-out -------------------------------
        _, deg_a0, err_a0 = serve_all(router)
        hot = {"occupancy": 0.92, "shed_delta": 3}
        actions_a = [scaler.tick(dict(hot))["action"]]
        clockd["t"] = 1.0
        actions_a.append(scaler.tick(dict(hot))["action"])
        ev = dict(scaler.last_event or {})
        _, deg_a1, err_a1 = serve_all(router)
        _, _, err_a2 = serve_all(router)   # steady pass: compiled programs
        versions = {
            rid: h.params_version for rid, h in router.replicas.items()
        }
        out["scale_out"] = {
            "actions": actions_a,
            "ticks_to_scale": len(actions_a),
            "replica": ev.get("replica"),
            "warm_compiles": ev.get("warm_compiles", 0),
            "moved": ev.get("moved", 0),
            "replicas_after": len(router.replicas),
            "params_version_uniform": len(set(versions.values())) == 1,
            "params_version": max(versions.values()),
            "quarantine_held": deg_a0 == 1 and deg_a1 == 1,
            "errors": err_a0 + err_a1 + err_a2,
        }
        tail_a = standby.poll()

        # --- PHASE B: trough -> drain-in (in-flight pinned) -----------
        victim = sorted(router.replicas)[-1]
        owned = [t for t, e in router.directory.items()
                 if e.owner == victim and t != names[1]]
        inflight = [
            router.submit(pools[t][1], 10.0, tenant=t) for t in owned[:4]
        ]
        clockd["t"] = 10.0   # past the scale-out cool-down
        actions_b = []
        for _ in range(60):
            actions_b.append(scaler.tick({"occupancy": 0.02})["action"])
            clockd["t"] += 1.0
            if actions_b[-1] == "drain_in":
                break
            if actions_b[-1] == "pending":
                time.sleep(0.05)   # real queue draining on the victim
        ev2 = dict(scaler.last_event or {})
        inflight_drain_ok = all(
            "label" in f.result(timeout=30.0) for f in inflight
        )
        _, deg_b, err_b = serve_all(router)
        out["drain_in"] = {
            "replica": ev2.get("replica"),
            "victim_matches": ev2.get("replica") == victim,
            "inflight_at_drain": len(inflight),
            "inflight_survived": inflight_drain_ok,
            "moved": ev2.get("moved", 0),
            "replicas_after": len(router.replicas),
            "tenants_intact": len(router.directory) == T,
            "drained": actions_b[-1] == "drain_in",
            "errors": err_b,
        }
        tail_b = standby.poll()

        # --- PHASE C: second ramp -> kill-9 -> promotion --------------
        clockd["t"] += 10.0   # past the drain-in cool-down
        scaler.tick(dict(hot))
        clockd["t"] += 1.0
        actions_c = scaler.tick(dict(hot))["action"]
        ev3 = dict(scaler.last_event or {})
        _, _, err_c = serve_all(router)
        dir_before = router.directory_view()
        placement_before = router.placement.owners(names)
        inflight2 = [
            router.submit(pools[t][1], 10.0, tenant=t)
            for t in names[2:8] if t != names[1]
        ]
        # Kill-9: the router object (and its breaker) is GONE. The
        # replica engines are separate "processes" and keep working the
        # queues they own; the zombie control plane object survives to
        # prove the lease fence below.
        zombie_journal = journal
        routers.remove(router)
        del router, control

        # The promotion window: known tenants get degraded NOTA in
        # microseconds — served, never dropped; unknown tenants are
        # refused loudly.
        window_deg = 0
        for t in names[:3]:
            v = standby.classify(pools[t][0], tenant=t)
            window_deg += bool(v.get("degraded") and v.get("failover"))
        try:
            standby.classify(pools[names[0]][0], tenant="t99")
            unknown_refused = False
        except ValueError:
            unknown_refused = True

        # The standby has NOT polled since before the second scale-out:
        # r02's replica_add + tenant moves are exactly what promote()'s
        # final catch-up replay must fold (final_tail_ops >= 1 below).
        live_handles = {
            rid: h for rid, h in handles.items() if rid != victim
        }
        promo = standby.promote(
            live_handles,
            breaker=CircuitBreaker(failure_threshold=3, open_s=1.0),
            queue_capacity_per_replica=64,
        )
        routers.append(standby.router)
        journals.append(standby.journal)
        dir_after = standby.router.directory_view()
        inflight_kill_ok = all(
            "label" in f.result(timeout=30.0) for f in inflight2
        )
        _, deg_p, err_p = serve_all(standby)

        # The zombie primary tries to append behind the new leader's
        # back: the lease check must refuse (split-brain fence).
        try:
            zombie_journal.append(
                "tenant_threshold", tenant=names[3], threshold=0.4
            )
            split_brain_refused = False
        except JournalError:
            split_brain_refused = True
        # ... while the PROMOTED writer's journaled ops land fine.
        control3 = FleetControl(
            standby.router, journal=standby.journal, logger=own_logger,
        )
        control3.set_nota_threshold(names[2], 0.45)
        promoted_writer_ok = (
            standby.journal.materialize()
            .tenants[names[2]]["nota_threshold"] == 0.45
        )

        out["promotion"] = {
            "scale_out2_replica": ev3.get("replica"),
            "second_ramp_action": actions_c,
            "replicas_at_kill": len(live_handles),
            "directory_bitwise": dir_after == dir_before,
            "placement_identical":
                standby.router.placement.owners(names) == placement_before,
            "tenants_lost": T - len(standby.router.directory),
            "degraded_during_promotion": window_deg,
            "unknown_tenant_refused": unknown_refused,
            "inflight_at_kill": len(inflight2),
            "inflight_survived": inflight_kill_ok,
            "promote_s": round(promo["promote_s"], 4),
            "final_tail_ops": promo["final_tail_ops"],
            "applied": promo["applied"],
            "lease_epoch": promo["lease_epoch"],
            "split_brain_refused": split_brain_refused,
            "promoted_writer_ok": promoted_writer_ok,
            "quarantine_held": deg_p == 1,
            "errors": err_p + err_c,
        }
        out["standby"] = {
            "tail_ops_scale": tail_a,
            "tail_ops_drain": tail_b,
            "applied": standby.applied,
        }
        steady = sum(
            h.stats_snapshot()["steady_recompiles"]
            for h in handles.values()
        )
        out["zero_bands"] = {
            "dropped_during_scale":
                out["scale_out"]["errors"] + out["drain_in"]["errors"],
            "dropped_during_promotion":
                out["promotion"]["errors"]
                + (0 if inflight_kill_ok else len(inflight2)),
            "tenants_lost": out["promotion"]["tenants_lost"],
            "steady_recompiles": steady,
        }
        out["passed"] = check_elastic_drill(out)
        return out
    finally:
        for r in routers:
            r.close()
        for j in journals:
            j.close()
        for h in handles.values():
            try:
                h.close()
            except Exception:  # noqa: BLE001 — already closed is fine
                pass
        if logger is None:
            own_logger.close()
        tmp.cleanup()


def check_elastic_drill(out: dict) -> bool:
    """The drill's acceptance: hysteresis-gated scale-out with the
    newcomer caught up + pre-warmed BEFORE traffic, drain-in that
    retires only after the queue empties (in-flight pinned), standby
    promotion rebuilding the directory bitwise with the zombie primary
    fenced — and every elasticity zero-band at zero."""
    so = out.get("scale_out", {})
    di = out.get("drain_in", {})
    pr = out.get("promotion", {})
    sb = out.get("standby", {})
    zb = out.get("zero_bands", {})
    return bool(
        so.get("actions") == ["none", "scale_out"]
        and so.get("replicas_after") == 2
        and so.get("warm_compiles", 0) >= 1
        and so.get("moved", 0) >= 1
        and so.get("params_version_uniform")
        and so.get("params_version") == 1
        and so.get("quarantine_held")
        and di.get("victim_matches")
        and di.get("drained")
        and di.get("replicas_after") == 1
        and di.get("tenants_intact")
        and di.get("inflight_at_drain", 0) >= 1
        and di.get("inflight_survived")
        and pr.get("second_ramp_action") == "scale_out"
        and pr.get("replicas_at_kill") == 2
        and pr.get("directory_bitwise")
        and pr.get("placement_identical")
        and pr.get("tenants_lost") == 0
        and pr.get("degraded_during_promotion", 0) >= 1
        and pr.get("unknown_tenant_refused")
        and pr.get("inflight_survived")
        and pr.get("final_tail_ops", 0) >= 1
        and pr.get("split_brain_refused")
        and pr.get("promoted_writer_ok")
        and pr.get("quarantine_held")
        and sb.get("tail_ops_scale", 0) >= 1
        and sb.get("tail_ops_drain", 0) >= 1
        and zb.get("dropped_during_scale") == 0
        and zb.get("dropped_during_promotion") == 0
        and zb.get("tenants_lost") == 0
        and zb.get("steady_recompiles") == 0
    )


def fleet_obs_drill(seed: int = 0, fleet_dir: str | None = None) -> dict:
    """The fleet observability drill (ISSUE 17): a 3-replica fleet laid
    out as the MULTI-STREAM run-dir convention tools/fleet_report.py
    ingests — ``router/`` (router-process telemetry: hops, rollups,
    journal-op events), one dir per replica (identity-stamped engine
    streams with the sampled request waterfalls), ``journal/`` (the
    WAL) — driven with open-loop load through one scale-out, one
    replica kill, and one fan-out publish mid-run. The stitched report
    is the system under test: every sampled hop must stitch to its
    replica-side trace (unstitched_frac=0), no replica trace may go
    unclaimed (orphan_spans=0), the journal ops must land in the
    timeline in the order they were fired, and fleet_report --check
    must be green. Stamped into OBSFLEET_r*.json."""
    from pathlib import Path

    import jax

    import fleet_report

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
    from induction_network_on_fewrel_tpu.fleet import (
        FleetControl,
        FleetRouter,
        InProcessReplica,
    )
    from induction_network_on_fewrel_tpu.fleet.journal import FleetJournal
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.serving.batcher import Saturated
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
    from induction_network_on_fewrel_tpu.utils.metrics import MetricsLogger

    if fleet_dir is None:
        raise ValueError("fleet_obs_drill needs a fleet dir (--run_dir)")
    root = Path(fleet_dir)
    root.mkdir(parents=True, exist_ok=True)
    R, T = 3, 6
    cfg = ExperimentConfig(
        model="induction", encoder="cnn", hidden_size=16,
        vocab_size=122, word_dim=8, pos_dim=2, max_length=16,
        induction_dim=8, ntn_slices=4, routing_iters=2,
        n=3, train_n=3, k=2, q=2, device="cpu", seed=seed,
    )
    vocab = make_synthetic_glove(
        vocab_size=cfg.vocab_size - 2, word_dim=cfg.word_dim
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(seed),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    datasets = [
        make_synthetic_fewrel(
            num_relations=cfg.n, instances_per_relation=cfg.k + 6,
            vocab_size=cfg.vocab_size - 2, seed=seed + 101 * d,
        )
        for d in range(4)
    ]
    loggers: list = []

    def mk(rid):
        # ONE stream per process-equivalent: each replica gets its own
        # run dir + logger stamped with its serve identity — what makes
        # the streams separable again after fleet_report merges them.
        lg = MetricsLogger(root / rid, quiet=True)
        lg.set_identity("serve", replica=rid)
        loggers.append(lg)
        return InProcessReplica(rid, InferenceEngine(
            model, params, cfg, tok, k=cfg.k, buckets=(1, 2, 4),
            logger=lg,
        ))

    replicas = {f"r{i + 1:02d}": mk(f"r{i + 1:02d}") for i in range(R)}
    rlog = MetricsLogger(root / "router", quiet=True)
    rlog.set_identity("router")
    loggers.append(rlog)
    router = FleetRouter(dict(replicas), logger=rlog, trace_sample=0.5,
                         queue_capacity_per_replica=64)
    journal = FleetJournal(root / "journal", logger=rlog)
    control = FleetControl(router, journal=journal)
    out: dict = {"replicas": R, "tenants": T, "seed": seed}
    futs: list = []
    try:
        names = [f"t{i:02d}" for i in range(T)]
        for i, t in enumerate(names):
            control.register_tenant(t, datasets[i % 4])
        for h in router.replicas.values():
            h.warmup()
        pools = {
            t: [
                inst for rel in datasets[i % 4].rel_names
                for inst in datasets[i % 4].instances[rel][cfg.k:]
            ]
            for i, t in enumerate(names)
        }

        def open_loop(n, phase):
            # Open loop: fixed arrival cadence, completions collected at
            # the end — queueing shows up in the hop segments instead of
            # gating the arrival rate.
            for s in range(n):
                t = names[(s + phase) % T]
                try:
                    futs.append(router.submit(
                        pools[t][s % len(pools[t])], 10.0, tenant=t,
                    ))
                except Saturated:
                    pass
                time.sleep(0.002)

        open_loop(24, 0)
        # Incident 1: scale-out (journals replica_add; churn re-placed).
        control.add_replica(mk(f"r{R + 1:02d}"))
        control.replace_tenants()
        router.replicas[f"r{R + 1:02d}"].warmup()
        open_loop(24, 1)
        # Incident 2: replica kill. The engine object keeps draining its
        # queue (in-flight sampled requests still land their replica
        # traces — nothing goes orphan), but placement fails over and
        # the timeline gets its fault record.
        victim = router.directory[names[0]].owner
        router.mark_replica_dead(victim, reason="drill")
        control.replace_tenants()
        open_loop(24, 2)
        # Incident 3: fan-out publish (journals publish_commit).
        control.publish_params(params)
        open_loop(24, 3)
        served = degraded = 0
        for f in futs:
            v = f.result(timeout=30.0)
            served += 1
            degraded += bool(v.get("degraded"))
        out["requests"] = {"submitted": len(futs), "served": served,
                           "degraded": degraded}
        out["victim"] = victim
        router.emit_stats()
    finally:
        router.close()
        for lg in loggers:
            lg.close()

    # The stitched report IS the acceptance: run the same code path
    # ``fleet_report --check`` runs, on the layout just written.
    router_dir, rep_dirs, jdir = fleet_report.discover(root, None, [], None)
    report = fleet_report.build_report(
        root, router_dir, rep_dirs, jdir,
        skew_bound_ms=250.0, n_waterfalls=3,
    )
    st = report["stitching"]
    hops = [r for r in fleet_report.load_stream(router_dir)
            if r.get("kind") == "hop"]

    def pct(vals, q):
        if not vals:
            return None
        xs = sorted(vals)
        return xs[min(len(xs) - 1, int(q / 100.0 * len(xs)))]

    hop_ms = [float(r["hop_ms"]) for r in hops]
    router_ms = [float(r["router_ms"]) for r in hops]
    out["stitching"] = {
        "hop_records": st["hop_records"],
        "stitched": st["stitched"],
        "stitch_coverage": round(
            st["stitched"] / st["hop_records"], 4
        ) if st["hop_records"] else 0.0,
        "unstitched_frac": st["unstitched_frac"],
        "orphan_spans": st["orphan_spans"],
    }
    out["hop"] = {
        "hop_ms_p50": pct(hop_ms, 50), "hop_ms_p99": pct(hop_ms, 99),
        "router_ms_p50": pct(router_ms, 50),
        "router_ms_p99": pct(router_ms, 99),
    }
    out["clock"] = {
        "max_offset_ms": report["worst_skew_ms"],
        "per_replica": report["clock_offset_ms"],
    }
    tl = report["timeline"]["raw"]

    def first(pred):
        return next((i for i, e in enumerate(tl) if pred(e)), None)

    i_add = first(lambda e: "journal replica_add" in e["event"])
    i_kill = first(lambda e: "DEAD" in e["event"])
    i_pub = first(lambda e: "journal publish_commit" in e["event"])
    out["timeline"] = {
        "events": report["timeline"]["events"],
        "unplaced": report["timeline"]["unplaced_events"],
        "journal_ops": sum(
            1 for e in tl if e["event"].startswith("journal ")
        ),
        "incidents_ordered": (
            None not in (i_add, i_kill, i_pub)
            and i_add < i_kill < i_pub
        ),
    }
    out["zero_bands"] = {
        "orphan_spans": st["orphan_spans"],
        "unstitched_frac": st["unstitched_frac"],
    }
    out["check_failures"] = report["failures"]
    out["waterfalls_rendered"] = len(report["waterfalls"])
    out["passed"] = bool(
        not report["failures"]
        and st["hop_records"] >= 10
        and out["stitching"]["stitch_coverage"] == 1.0
        and st["orphan_spans"] == 0
        and out["timeline"]["incidents_ordered"]
        and out["timeline"]["unplaced"] == 0
        and out["waterfalls_rendered"] >= 1
        and out["requests"]["served"] == out["requests"]["submitted"]
    )
    return out


# --- quantized-serving A/B drill (ISSUE 18) ---------------------------------
#
# Three arms — f32 / bf16 / int8 resident class vectors — against the same
# synthetic checkpoint and the same seeded open-loop arrivals. Quantized
# arms shadow-score EVERY batch against f32 (quant_probe_every=1: the
# drill wants maximum parity evidence, production samples), so the
# artifact's verdict-agreement and margin-drift numbers cover the whole
# run, not a sample. The density section projects tenants-per-chip from
# the MEASURED resident bytes per tenant against a nominal budget — a
# projection, clearly labeled, because this drill runs on CPU; the real
# chip A/B is queued on the BASELINE.md backlog.

# Nominal per-chip budget for RESIDENT CLASS VECTORS (1 GiB): params,
# activations and XLA workspace own the rest of HBM. The projection's
# honesty lives in the ratio between arms, not the absolute count.
QUANT_RESIDENT_BUDGET_BYTES = 2**30

# Per-arm parity tolerance for the registry-vs-direct forward check:
# f32 residents must match the episodic path to float error; quantized
# residents carry real quantization error, gated well inside the 0.25
# margin-drift band (the VERDICT agreement floor is the real quality
# gate for those arms).
QUANT_PARITY_TOL = {"f32": 1e-4, "bf16": 0.25, "int8": 0.25}


def run_quant_arm(args, ckpt, dtype: str, logger=None) -> dict:
    """One resident-dtype arm: fresh engine, registered tenants, parity
    check, open-loop phase, stats snapshot. Returns the arm record."""
    import numpy as np

    engine = build_engine(
        args, ckpt, "continuous", logger=logger,
        resident_dtype=dtype,
        quant_probe_every=0 if dtype == "f32" else 1,
    )
    try:
        tenants = register_tenants(engine, args)
        compiled = engine.warmup()
        parity = max(
            check_registry_parity(engine, ds, tenant=t)
            for t, ds in tenants.items()
        )
        print(f"[quant ab/{dtype}] warmup {compiled} programs, parity "
              f"max|delta| = {parity:.2e} (tol {QUANT_PARITY_TOL[dtype]})",
              file=sys.stderr)
        pools = _pools(tenants, args.K)
        rng = np.random.default_rng(args.seed)  # same arrivals per arm
        lat, rej, miss, dropped, wall, offered, _ = run_open(
            engine, pools, args.rate, args.duration, rng,
        )
        flat = _flat(lat)
        snap = engine.stats.snapshot(
            queue_depth=engine.batcher.queue_depth
        )
        resident = engine.registry.resident_bytes()
        quality = engine.stats.quality_snapshot()
        drifts = [
            q["quant_margin_drift"] for q in quality.values()
            if "quant_margin_drift" in q
        ]
        return {
            "dtype": dtype,
            "warmup_compiles": compiled,
            "parity_max_delta": parity,
            "parity_tol": QUANT_PARITY_TOL[dtype],
            "offered_qps": round(offered / wall, 1),
            "qps": round(len(flat) / wall, 1),
            "p50_ms": pct_ms(flat, 50),
            "p99_ms": pct_ms(flat, 99),
            "served": snap["served"],
            "rejected": rej,
            "deadline_miss": miss,
            "dropped": dropped,
            "steady_recompiles": snap["steady_recompiles"],
            "resident_bytes": snap["resident_bytes"],
            "resident_bytes_per_tenant": round(
                sum(resident.values()) / max(len(resident), 1), 1
            ),
            "quant_probes": snap["quant_probes"],
            "quant_agreement": snap["quant_agreement"],
            "quant_margin_drift": round(
                sum(drifts) / len(drifts), 4
            ) if drifts else 0.0,
        }
    finally:
        engine.close()


def run_quant_ab(args, ckpt, logger=None) -> dict:
    """The three-arm drill + the density projection + the gates."""
    arms = {
        dt: run_quant_arm(args, ckpt, dt, logger=logger)
        for dt in ("f32", "bf16", "int8")
    }
    bpt = {dt: arms[dt]["resident_bytes_per_tenant"] for dt in arms}
    density = {
        "resident_budget_bytes_nominal": QUANT_RESIDENT_BUDGET_BYTES,
        "projection_note": (
            "tenants_per_chip = budget / measured bytes-per-tenant; a "
            "CPU-measured projection — real-chip A/B queued on the "
            "BASELINE.md backlog"
        ),
        "bytes_ratio_f32_over_int8": round(
            bpt["f32"] / max(bpt["int8"], 1e-9), 2
        ),
        "bytes_ratio_f32_over_bf16": round(
            bpt["f32"] / max(bpt["bf16"], 1e-9), 2
        ),
        "tenants_per_chip_projected": {
            dt: int(QUANT_RESIDENT_BUDGET_BYTES // max(bpt[dt], 1.0))
            for dt in arms
        },
    }
    out = {
        "arms": arms,
        "density": density,
        "parity_floor": 0.99,
        "margin_drift_band": 0.25,
        "zero_bands": {
            "dropped": sum(a["dropped"] for a in arms.values()),
            "steady_recompiles": sum(
                a["steady_recompiles"] for a in arms.values()
            ),
        },
    }
    out["check_failures"] = check_quant_ab(out)
    out["passed"] = not out["check_failures"]
    return out


def check_quant_ab(out: dict) -> list:
    """Gate the drill: every failure is a named string (stamped into the
    artifact so a red run says WHICH invariant broke)."""
    fails = []
    for name, v in out["zero_bands"].items():
        if v != 0:
            fails.append(f"zero_band:{name}={v}")
    for dt, arm in out["arms"].items():
        if not (arm["parity_max_delta"] < arm["parity_tol"]):
            fails.append(
                f"parity:{dt}={arm['parity_max_delta']:.3g}"
                f">={arm['parity_tol']}"
            )
        if dt != "f32":
            if arm["quant_probes"] <= 0:
                fails.append(f"no_probes:{dt}")
            if arm["quant_agreement"] < out["parity_floor"]:
                fails.append(
                    f"agreement:{dt}={arm['quant_agreement']:.4f}"
                    f"<{out['parity_floor']}"
                )
            if arm["quant_margin_drift"] > out["margin_drift_band"]:
                fails.append(
                    f"margin_drift:{dt}={arm['quant_margin_drift']:.4f}"
                    f">{out['margin_drift_band']}"
                )
    if out["density"]["bytes_ratio_f32_over_int8"] < 3.5:
        fails.append(
            f"density:f32/int8="
            f"{out['density']['bytes_ratio_f32_over_int8']}<3.5"
        )
    return fails


# --- mixed-geometry A/B drill (ISSUE 19) ------------------------------------
#
# Two arms against the same checkpoint and the same seeded arrivals:
# **tiered** (N-tier bucketed resident stacks, the serving default) vs
# **exact-N** (geometry_tiers="off" — one program family per distinct
# class count). Both arms serve the same mixed-N tenant set spanning the
# 3..40 range, then take a tier-crossing re-registration (a tenant that
# registered 7 of its 9 relations registers the rest, crossing the 8->16
# tier) and a resident-dtype flip mid-drill. The tiered arm must hold
# zero steady recompiles through BOTH (warm-before-swap) with its
# program count bounded by tiers x buckets x dtypes; the exact arm
# documents the recompile tax the tiers exist to remove.

# Class counts per co-resident tenant — the 3..40 tenant range from the
# ISSUE acceptance. Under DEFAULT_TIERS they collapse to 5 tiers; the
# exact arm compiles one family per distinct N (plus one more when the
# crosser grows 7 -> 9).
GEOM_TENANT_N = (3, 5, 14, 24, 40)
GEOM_CROSSER_DS_N = 9     # the crosser's full relation set
GEOM_CROSSER_START = 7    # registered first (tier 8); +2 crosses to 16
GEOM_PARITY_TOL_F32 = 1e-4    # both arms serve f32 residents at parity
GEOM_PARITY_TOL_BF16 = 0.25   # the flipped tenant, after the flip


def register_geom_tenants(engine, args) -> dict:
    """The mixed-geometry tenant set: one synthetic relation corpus per
    entry of ``GEOM_TENANT_N`` plus the crosser at its starting class
    count; returns {tenant: dataset} (the crosser's ds carries all
    ``GEOM_CROSSER_DS_N`` relations — re-registering it later IS the
    tier crossing)."""
    from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel

    tenants = {}
    for t, n in enumerate(GEOM_TENANT_N):
        name = f"geo{t}_n{n}"
        ds = make_synthetic_fewrel(
            num_relations=n, instances_per_relation=args.K + 10,
            vocab_size=2000, seed=args.seed + 101 * t,
        )
        engine.register_dataset(ds, tenant=name)
        tenants[name] = ds
    ds = make_synthetic_fewrel(
        num_relations=GEOM_CROSSER_DS_N,
        instances_per_relation=args.K + 10,
        vocab_size=2000, seed=args.seed + 977,
    )
    engine.register_dataset(ds, tenant="crosser",
                            max_classes=GEOM_CROSSER_START)
    tenants["crosser"] = ds
    return tenants


def run_geom_arm(args, ckpt, tiers_spec: str, label: str,
                 logger=None) -> dict:
    """One geometry arm: mixed-N tenants, warmup, parity, open-loop
    phase 1, tier-crossing re-registration + dtype flip, open-loop
    phase 2, stats. Returns the arm record."""
    import numpy as np

    engine = build_engine(
        args, ckpt, "continuous", logger=logger,
        geometry_tiers=tiers_spec,
    )
    try:
        tenants = register_geom_tenants(engine, args)
        compiled = engine.warmup()
        parity = max(
            check_registry_parity(engine, ds, tenant=t)
            for t, ds in tenants.items()
        )
        tier_by_tenant = {
            t: engine.registry.snapshot(t).n_tier for t in tenants
        }
        print(f"[geom ab/{label}] warmup {compiled} programs, "
              f"tiers {sorted(set(tier_by_tenant.values()))}, parity "
              f"max|delta| = {parity:.2e}", file=sys.stderr)
        pools = _pools(tenants, args.K)
        rng = np.random.default_rng(args.seed)  # same arrivals per arm
        lat1, rej1, miss1, drop1, wall1, off1, _ = run_open(
            engine, pools, args.rate, args.duration, rng,
        )
        # -- mid-drill geometry churn --------------------------------------
        # Tier crossing: the crosser registers its remaining relations
        # (7 -> 9 classes; under DEFAULT_TIERS that crosses 8 -> 16 and
        # the engine warms the new tier BEFORE the registry swap).
        engine.register_dataset(tenants["crosser"], tenant="crosser")
        cross_tier = engine.registry.snapshot("crosser").n_tier
        # Dtype flip: the smallest tenant rolls to bf16 (warm-first,
        # same contract as the quant rollback path).
        flip_tenant = f"geo0_n{GEOM_TENANT_N[0]}"
        engine.set_resident_dtype(flip_tenant, "bf16")
        flip_parity = check_registry_parity(
            engine, tenants[flip_tenant], tenant=flip_tenant
        )
        lat2, rej2, miss2, drop2, wall2, off2, _ = run_open(
            engine, pools, args.rate, args.duration, rng,
        )
        flat = _flat(lat1) + _flat(lat2)
        wall = wall1 + wall2
        snap = engine.stats.snapshot(queue_depth=engine.batcher.queue_depth)
        return {
            "arm": label,
            "geometry_tiers": tiers_spec,
            "tenants": len(tenants),
            "tenant_classes": {
                t: len(engine.registry.snapshot(t).names) for t in tenants
            },
            "tier_by_tenant": tier_by_tenant,
            "warmup_compiles": compiled,
            "programs_compiled": engine.programs.compiles,
            "program_cache_keys": len(engine.programs._exe),
            "parity_max_delta": parity,
            "parity_tol": GEOM_PARITY_TOL_F32,
            "tier_crossing": {
                "tenant": "crosser",
                "classes": f"{GEOM_CROSSER_START}->{GEOM_CROSSER_DS_N}",
                "tier_after": cross_tier,
            },
            "dtype_flip": {
                "tenant": flip_tenant, "dtype": "bf16",
                "parity_max_delta": flip_parity,
                "parity_tol": GEOM_PARITY_TOL_BF16,
            },
            "offered_qps": round((off1 + off2) / wall, 1),
            "qps": round(len(flat) / wall, 1),
            "p50_ms": pct_ms(flat, 50),
            "p99_ms": pct_ms(flat, 99),
            "served": snap["served"],
            "rejected": rej1 + rej2,
            "deadline_miss": miss1 + miss2,
            "dropped": drop1 + drop2,
            "steady_recompiles": snap["steady_recompiles"],
            "resident_bytes": snap["resident_bytes"],
        }
    finally:
        engine.close()


def run_geom_ab(args, ckpt, logger=None) -> dict:
    """Tiered vs exact-N arms + the scenario (N, K) grid leg + gates."""
    from induction_network_on_fewrel_tpu.serving.geometry import (
        DEFAULT_TIERS,
        program_bound,
        tiers_spec,
    )

    tiered_spec = tiers_spec(DEFAULT_TIERS)
    arms = {
        "tiered": run_geom_arm(args, ckpt, tiered_spec, "tiered",
                               logger=logger),
        "exact": run_geom_arm(args, ckpt, "off", "exact", logger=logger),
    }
    buckets = tuple(int(b) for b in args.buckets.split(","))
    # Bound for the whole drill: f32 everywhere plus the one bf16 flip.
    bound = program_bound(DEFAULT_TIERS, buckets, n_dtypes=2)
    # The paper's (N, K) eval grid, from the scenario harness's
    # miniature leg (same world tests/test_scenarios.py replays): each
    # point carries accuracy + acc_ci95 for bench_trend's bands.
    import scenarios

    grid_res = scenarios.run_tier1(seed=args.seed + 1)
    grid = {
        key: {
            "n": leg["n"], "k": leg["k"],
            "accuracy": leg["accuracy"], "acc_ci95": leg["acc_ci95"],
        }
        for key, leg in grid_res["grid"].items()
    }
    out = {
        "arms": arms,
        "program_bound_tiered": bound,
        "grid": grid,
        "zero_bands": {
            "tiered_dropped": arms["tiered"]["dropped"],
            "tiered_steady_recompiles":
                arms["tiered"]["steady_recompiles"],
        },
        # The tax the tiers remove: the exact arm recompiles ON the
        # query path when the crosser re-registers (7 -> 9 has no
        # warmed program family), the tiered arm must not.
        "exact_arm_steady_recompiles": arms["exact"]["steady_recompiles"],
    }
    out["check_failures"] = check_geom_ab(out)
    out["passed"] = not out["check_failures"]
    return out


def check_geom_ab(out: dict) -> list:
    """Gate the drill: every failure is a named string (stamped into
    the artifact so a red run says WHICH invariant broke)."""
    fails = []
    for name, v in out["zero_bands"].items():
        if v != 0:
            fails.append(f"zero_band:{name}={v}")
    t, e = out["arms"]["tiered"], out["arms"]["exact"]
    if not (t["parity_max_delta"] < t["parity_tol"]):
        fails.append(
            f"parity:tiered={t['parity_max_delta']:.3g}"
            f">={t['parity_tol']}"
        )
    if not (e["parity_max_delta"] < e["parity_tol"]):
        fails.append(
            f"parity:exact={e['parity_max_delta']:.3g}"
            f">={e['parity_tol']}"
        )
    for label, arm in out["arms"].items():
        fp = arm["dtype_flip"]
        if not (fp["parity_max_delta"] < fp["parity_tol"]):
            fails.append(
                f"flip_parity:{label}={fp['parity_max_delta']:.3g}"
                f">={fp['parity_tol']}"
            )
    if t["program_cache_keys"] > out["program_bound_tiered"]:
        fails.append(
            f"program_bound:tiered={t['program_cache_keys']}"
            f">{out['program_bound_tiered']}"
        )
    if t["program_cache_keys"] >= e["program_cache_keys"]:
        fails.append(
            f"no_program_win:tiered={t['program_cache_keys']}"
            f">=exact={e['program_cache_keys']}"
        )
    if e["steady_recompiles"] == 0:
        fails.append(
            "exact_arm_recompile_tax_missing: the exact arm's tier "
            "crossing should recompile on the query path"
        )
    if not out["grid"]:
        fails.append("grid:empty")
    return fails


def main(argv=None) -> int:
    args = parse_args(argv)
    import numpy as np

    from induction_network_on_fewrel_tpu.cli import select_device
    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    # ENV FINDING (round 15): the persistent XLA compile cache corrupts
    # the glibc heap on this image when one process both SERVES (live
    # engine programs) and TRAINS (the adaptation fine-tune) — the drill
    # segfaulted in the fine-tune's train dispatch with the cache on,
    # reproducibly, and is clean with it off (same class as the round-6
    # CLI --resume and round-10 profiler teardown crashes; BASELINE
    # round 15). serve.py --adapt deployments on this image should pass
    # --compile_cache off likewise (RUNBOOK §19).
    # --geom_ab also both serves and trains (the scenario-grid leg) in
    # one process, so it gets the same compile-cache opt-out.
    select_device(ExperimentConfig(device=args.device),
                  "off" if (args.adapt_drill or args.geom_ab) else "auto")

    tmp = None
    ckpt = args.ckpt
    if ckpt is None and not (args.adapt_drill or args.recovery_drill
                             or args.elastic_drill
                             or args.fleet_obs_drill):
        # --adapt_drill / --recovery_drill / --elastic_drill build
        # their own miniature worlds (the default synthetic checkpoint
        # would be dead weight — one more orbax world for no reason).
        tmp = tempfile.TemporaryDirectory(prefix="loadgen_")
        print("building synthetic-data checkpoint...", file=sys.stderr)
        # The quant A/B measures verdict agreement — it needs a model
        # with real margins, not fresh-init near-ties (see
        # make_synthetic_checkpoint).
        ckpt = make_synthetic_checkpoint(
            args, tmp.name, train_iters=60 if args.quant_ab else 0
        )

    arms = (
        ["continuous", "microbatch"] if args.scheduler == "ab"
        else [args.scheduler]
    )
    # Shared telemetry sinks (one metrics.jsonl across arms — records
    # carry the scheduler, so obs_report can split); SLO engines are
    # per-arm (fresh burn windows each).
    logger = recorder = capture = None
    if args.fleet_obs_drill:
        # The obs drill lays its OWN multi-stream convention under
        # --run_dir (router/, r*/, journal/) — a shared top-level
        # metrics.jsonl would be a fifth stream nothing reads.
        pass
    elif args.run_dir:
        from induction_network_on_fewrel_tpu.obs import (
            DiagnosticsCapture,
            FlightRecorder,
        )
        from induction_network_on_fewrel_tpu.utils.metrics import (
            MetricsLogger,
        )

        logger = MetricsLogger(args.run_dir)
        recorder = FlightRecorder(out_dir=args.run_dir)
        logger.add_hook(recorder.record_metric)
        capture = DiagnosticsCapture(
            args.run_dir, recorder=recorder, profile=args.slo_profile,
        )
    results = {}
    rc = 0
    try:
        if args.fleet > 0:
            # Fleet soak mode (ISSUE 13): standalone — the router tier
            # is the system under test, not the scheduler arms.
            soak = run_fleet_soak(args, ckpt, logger, recorder, capture)
            ok = soak.get("passed", False)
            pl, fp, rk = (soak.get("placement", {}),
                          soak.get("fanout_publish", {}),
                          soak.get("replica_kill", {}))
            print(f"[fleet soak] R={args.fleet} T={soak['tenants']} "
                  f"qps={soak.get('traffic', {}).get('qps')} "
                  f"publish_s={fp.get('publish_s')} "
                  f"uniform={fp.get('uniform')} "
                  f"dropped={fp.get('dropped')} "
                  f"recompiles={soak.get('zero_bands', {}).get('steady_recompiles')}; "
                  f"add churn {pl.get('add_churn_frac')} "
                  f"(bound {pl.get('add_churn_bound')}); "
                  f"kill: degraded={rk.get('degraded_served')} "
                  f"criticals={rk.get('criticals')} "
                  f"recovered={rk.get('recovered')}")
            if not ok:
                print("FAIL[fleet soak]: invariants did not hold",
                      file=sys.stderr)
                rc = 1
            # The miniature tier-1 leg (the band tests/test_fleet.py
            # replays) rides in the artifact — same world, same seed.
            tier1 = fleet_tier1_drill(seed=args.seed, logger=logger)
            if not tier1.get("passed"):
                print("FAIL[fleet tier1]: miniature drill failed",
                      file=sys.stderr)
                rc = 1
            report = {
                "config": {
                    "fleet": args.fleet, "tenants": args.tenants,
                    "N": args.N, "K": args.K, "buckets": args.buckets,
                    "queue_depth": args.queue_depth,
                    "concurrency": args.concurrency,
                    "duration": args.duration, "device": args.device,
                    "seed": args.seed,
                },
                **soak,
                "tier1": {
                    **tier1,
                    # Placement is a pure function of the ids: the gate
                    # pins the miniature numbers EXACTLY (a placement/
                    # hash change must re-emit the artifact).
                    "band": {"churn_frac_abs": 0.0},
                },
            }
            print(json.dumps({
                k: report[k] for k in
                ("config", "traffic", "per_replica", "placement",
                 "fanout_publish", "replica_kill", "zero_bands", "passed")
                if k in report
            }))
            if args.fleet_artifact:
                with open(args.fleet_artifact, "w") as f:
                    json.dump(report, f, indent=1)
                print(f"wrote {args.fleet_artifact}", file=sys.stderr)
            if args.run_dir:
                print(f"telemetry in {args.run_dir} — render with "
                      f"'python tools/obs_report.py {args.run_dir}'",
                      file=sys.stderr)
            return rc
        if args.recovery_drill:
            # Standalone mode (like --fleet): the durable control plane
            # is the system under test, on its own miniature journaled
            # fleet — the scheduler arms are skipped.
            drill = recovery_tier1_drill(seed=args.seed, logger=logger)
            rk, rep, tt = (drill["router_kill"], drill["replica_kill"],
                           drill["torn_tail"])
            print(f"[recovery drill/router-kill] bitwise="
                  f"{rk['directory_bitwise']} "
                  f"placement={rk['placement_identical']} "
                  f"lost={rk['tenants_lost']} "
                  f"reregistered={rk['reregistered']} "
                  f"caught_up={rk['caught_up']} "
                  f"uniform=v{rk['params_version']} "
                  f"errors={rk['errors']}")
            print(f"[recovery drill/replica-kill] victim={rep['victim']} "
                  f"attempts={rep['restart_attempts']} "
                  f"backoff_honored={rep['backoff_honored']} "
                  f"uniform={rep['params_version_uniform']} "
                  f"recovered={rep['recovered']} "
                  f"dropped={rep['dropped_during_catchup']} "
                  f"recompiles={rep['steady_recompiles']}")
            print(f"[recovery drill/torn-tail] "
                  f"refused={tt['append_refused_after_tear']} "
                  f"prefix={tt['prefix_recovered']} "
                  f"healed={tt['appendable_after_heal']}")
            if not drill["passed"]:
                print("FAIL[recovery drill]: durability invariants did "
                      "not hold", file=sys.stderr)
                rc = 1
            report = {
                "round": 1,
                "generated_by": "tools/loadgen.py --recovery_drill",
                **drill,
            }
            print(json.dumps({
                k: report[k] for k in
                ("replicas", "tenants", "zero_bands", "passed")
                if k in report
            }))
            if args.recovery_artifact:
                with open(args.recovery_artifact, "w") as fh:
                    json.dump(report, fh, indent=1)
                print(f"wrote {args.recovery_artifact}", file=sys.stderr)
            if args.run_dir:
                print(f"telemetry in {args.run_dir} — render with "
                      f"'python tools/obs_report.py {args.run_dir}'",
                      file=sys.stderr)
            return rc
        if args.elastic_drill:
            # Standalone mode (like --fleet): the elasticity tier is
            # the system under test, on its own miniature journaled
            # fleet + hot standby — the scheduler arms are skipped.
            drill = elastic_tier1_drill(seed=args.seed, logger=logger)
            so, di, pr = (drill["scale_out"], drill["drain_in"],
                          drill["promotion"])
            print(f"[elastic drill/scale-out] replica={so['replica']} "
                  f"ticks={so['ticks_to_scale']} "
                  f"warm_compiles={so['warm_compiles']} "
                  f"moved={so['moved']} "
                  f"uniform=v{so['params_version']} "
                  f"errors={so['errors']}")
            print(f"[elastic drill/drain-in] replica={di['replica']} "
                  f"inflight={di['inflight_at_drain']} "
                  f"survived={di['inflight_survived']} "
                  f"moved={di['moved']} "
                  f"tenants_intact={di['tenants_intact']} "
                  f"errors={di['errors']}")
            print(f"[elastic drill/promotion] "
                  f"bitwise={pr['directory_bitwise']} "
                  f"placement={pr['placement_identical']} "
                  f"lost={pr['tenants_lost']} "
                  f"degraded_window={pr['degraded_during_promotion']} "
                  f"tail_ops={pr['final_tail_ops']} "
                  f"split_brain_refused={pr['split_brain_refused']} "
                  f"promote_s={pr['promote_s']} "
                  f"errors={pr['errors']}")
            if not drill["passed"]:
                print("FAIL[elastic drill]: elasticity invariants did "
                      "not hold", file=sys.stderr)
                rc = 1
            report = {
                "round": 1,
                "generated_by": "tools/loadgen.py --elastic_drill",
                **drill,
            }
            print(json.dumps({
                k: report[k] for k in
                ("replicas_start", "tenants", "zero_bands", "passed")
                if k in report
            }))
            if args.elastic_artifact:
                with open(args.elastic_artifact, "w") as fh:
                    json.dump(report, fh, indent=1)
                print(f"wrote {args.elastic_artifact}", file=sys.stderr)
            if args.run_dir:
                print(f"telemetry in {args.run_dir} — render with "
                      f"'python tools/obs_report.py {args.run_dir}'",
                      file=sys.stderr)
            return rc
        if args.fleet_obs_drill:
            # Standalone mode (like --fleet): the observability plane
            # is the system under test, on its own miniature fleet laid
            # out as the fleet_report run-dir convention.
            drill = fleet_obs_drill(seed=args.seed,
                                    fleet_dir=args.run_dir)
            st, hp, tl = (drill["stitching"], drill["hop"],
                          drill["timeline"])
            print(f"[fleet obs drill] hops={st['hop_records']} "
                  f"coverage={st['stitch_coverage']} "
                  f"unstitched_frac={st['unstitched_frac']} "
                  f"orphans={st['orphan_spans']} "
                  f"hop_p50={hp['hop_ms_p50']}ms "
                  f"hop_p99={hp['hop_ms_p99']}ms "
                  f"router_p50={hp['router_ms_p50']}ms; "
                  f"timeline events={tl['events']} "
                  f"journal_ops={tl['journal_ops']} "
                  f"incidents_ordered={tl['incidents_ordered']} "
                  f"check_failures={len(drill['check_failures'])}")
            if not drill["passed"]:
                print("FAIL[fleet obs drill]: stitching/timeline "
                      "invariants did not hold", file=sys.stderr)
                rc = 1
            report = {
                "round": 1,
                "generated_by": "tools/loadgen.py --fleet_obs_drill",
                **drill,
            }
            print(json.dumps({
                k: report[k] for k in
                ("replicas", "tenants", "stitching", "hop", "timeline",
                 "zero_bands", "passed")
                if k in report
            }))
            if args.obsfleet_artifact:
                with open(args.obsfleet_artifact, "w") as fh:
                    json.dump(report, fh, indent=1)
                print(f"wrote {args.obsfleet_artifact}", file=sys.stderr)
            print(f"fleet layout in {args.run_dir} — render with "
                  f"'python tools/fleet_report.py {args.run_dir}'",
                  file=sys.stderr)
            return rc
        if args.adapt_drill:
            # Standalone mode (like --fleet): the adaptation loop is the
            # system under test, on its own miniature world — the
            # scheduler arms are skipped.
            drill = adapt_tier1_drill(
                seed=args.seed, logger=logger, recorder=recorder,
                capture=capture,
            )
            s, f = drill["success"], drill["canary_failure"]
            print(f"[adapt drill/success] tripped={s.get('tripped')} "
                  f"({s.get('trigger_feature')}) "
                  f"nota {s.get('nota_healthy')} -> "
                  f"{s.get('nota_shifted')} -> {s.get('nota_post')}; "
                  f"finetune {s.get('finetune_s')}s canary="
                  f"{s.get('canary_passed')} publish "
                  f"{s.get('publish_s')}s uniform="
                  f"{s.get('versions_uniform')} "
                  f"dropped={s.get('dropped_during_publish')} "
                  f"recompiles={s.get('steady_recompiles')} "
                  f"verified={s.get('verified')} "
                  f"recover {s.get('recover_s')}s")
            print(f"[adapt drill/canary-failure] tripped={f.get('tripped')} "
                  f"backoff_honored={f.get('backoff_honored')} "
                  f"exhausted={f.get('exhausted')} "
                  f"criticals={f.get('exhausted_criticals')} "
                  f"quarantined={f.get('quarantined')} "
                  f"publishes={f.get('unexpected_publishes')} "
                  f"cleaned={f.get('candidates_cleaned')}")
            if not drill["passed"]:
                print("FAIL[adapt drill]: the loop did not detect/adapt/"
                      "gate/verify (or contain) as required",
                      file=sys.stderr)
                rc = 1
            report = {
                "round": 1,
                "generated_by": "tools/loadgen.py --adapt_drill",
                **drill,
                # The zero-bands tools/bench_trend.py folds: the
                # adaptation publish must drop nothing and recompile
                # nothing, and the failure arm must publish NOTHING.
                "zero_bands": {
                    "dropped_during_publish":
                        s.get("dropped_during_publish"),
                    "steady_recompiles": s.get("steady_recompiles"),
                    "unexpected_publishes": f.get("unexpected_publishes"),
                },
            }
            print(json.dumps({
                k: report[k] for k in
                ("world", "zero_bands", "passed") if k in report
            }))
            if args.adapt_artifact:
                with open(args.adapt_artifact, "w") as fh:
                    json.dump(report, fh, indent=1)
                print(f"wrote {args.adapt_artifact}", file=sys.stderr)
            if args.run_dir:
                print(f"telemetry in {args.run_dir} — render with "
                      f"'python tools/obs_report.py {args.run_dir}'",
                      file=sys.stderr)
            return rc
        if args.quant_ab:
            # Standalone mode (like --fleet): the quantized data plane
            # is the system under test — the scheduler arms are skipped.
            drill = run_quant_ab(args, ckpt, logger=logger)
            den = drill["density"]
            for dt, a in drill["arms"].items():
                print(f"[quant ab/{dt}] qps={a['qps']} "
                      f"p50={a['p50_ms']}ms p99={a['p99_ms']}ms "
                      f"bytes/tenant={a['resident_bytes_per_tenant']} "
                      f"probes={a['quant_probes']} "
                      f"agreement={a['quant_agreement']} "
                      f"margin_drift={a['quant_margin_drift']} "
                      f"dropped={a['dropped']} "
                      f"recompiles={a['steady_recompiles']}")
            print(f"[quant ab/density] f32/int8 bytes ratio "
                  f"{den['bytes_ratio_f32_over_int8']}x, projected "
                  f"tenants/chip {den['tenants_per_chip_projected']}")
            if not drill["passed"]:
                print(f"FAIL[quant ab]: {drill['check_failures']}",
                      file=sys.stderr)
                rc = 1
            report = {
                "round": 1,
                "generated_by": "tools/loadgen.py --quant_ab",
                "config": {
                    "tenants": args.tenants, "N": args.N, "K": args.K,
                    "buckets": args.buckets, "rate": args.rate,
                    "duration": args.duration, "device": args.device,
                    "seed": args.seed,
                },
                **drill,
            }
            print(json.dumps({
                k: report[k] for k in
                ("config", "density", "zero_bands", "passed")
                if k in report
            }))
            if args.quant_artifact:
                with open(args.quant_artifact, "w") as fh:
                    json.dump(report, fh, indent=1)
                print(f"wrote {args.quant_artifact}", file=sys.stderr)
            if args.run_dir:
                print(f"telemetry in {args.run_dir} — render with "
                      f"'python tools/obs_report.py {args.run_dir}'",
                      file=sys.stderr)
            return rc
        if args.geom_ab:
            # Standalone mode (like --quant_ab): the geometry plane is
            # the system under test — the scheduler arms are skipped.
            drill = run_geom_ab(args, ckpt, logger=logger)
            for label, a in drill["arms"].items():
                print(f"[geom ab/{label}] programs="
                      f"{a['program_cache_keys']} "
                      f"(compiled {a['programs_compiled']}) "
                      f"qps={a['qps']} p50={a['p50_ms']}ms "
                      f"p99={a['p99_ms']}ms "
                      f"parity={a['parity_max_delta']:.2e} "
                      f"dropped={a['dropped']} "
                      f"recompiles={a['steady_recompiles']}")
            print(f"[geom ab/grid] " + " ".join(
                f"{k}={v['accuracy']}±{v['acc_ci95']}"
                for k, v in drill["grid"].items()
            ))
            if not drill["passed"]:
                print(f"FAIL[geom ab]: {drill['check_failures']}",
                      file=sys.stderr)
                rc = 1
            report = {
                "round": 1,
                "generated_by": "tools/loadgen.py --geom_ab",
                "config": {
                    "tenant_classes": list(GEOM_TENANT_N)
                    + [GEOM_CROSSER_START],
                    "K": args.K, "buckets": args.buckets,
                    "rate": args.rate, "duration": args.duration,
                    "device": args.device, "seed": args.seed,
                },
                **drill,
            }
            print(json.dumps({
                k: report[k] for k in
                ("config", "program_bound_tiered", "zero_bands",
                 "exact_arm_steady_recompiles", "passed")
                if k in report
            }))
            if args.geom_artifact:
                with open(args.geom_artifact, "w") as fh:
                    json.dump(report, fh, indent=1)
                print(f"wrote {args.geom_artifact}", file=sys.stderr)
            if args.run_dir:
                print(f"telemetry in {args.run_dir} — render with "
                      f"'python tools/obs_report.py {args.run_dir}'",
                      file=sys.stderr)
            return rc
        for arm in arms:
            rng = np.random.default_rng(args.seed)  # same arrivals per arm
            engine = build_engine(
                args, ckpt, arm, logger=logger,
                slo=build_slo(args, logger=logger, recorder=recorder,
                              capture=capture),
            )
            try:
                swap_fn = None
                if args.swap_drill:
                    # Re-publish the engine's own weights: the full swap
                    # machinery runs (re-distill every slot, republish
                    # every tenant, bump params_version) under live load —
                    # the drill measures disruption, not verdict change.
                    swap_fn = lambda e=engine: e.publish_params(e.params)  # noqa: E731
                results[arm] = drive_one(engine, args, rng, swap_fn=swap_fn)
            finally:
                engine.close()

            r = results[arm]
            if not r.get("parity_ok"):
                print(f"FAIL[{arm}]: registry parity out of tolerance",
                      file=sys.stderr)
                rc = 1
            snap = r.get("stats", {})
            print(f"[{arm}] occupancy {snap.get('batch_occupancy')} "
                  f"served {snap.get('served')} "
                  f"recompiles {snap.get('steady_recompiles')}")
            if snap.get("steady_recompiles", 0) > 0:
                print(f"FAIL[{arm}]: query path recompiled after warmup",
                      file=sys.stderr)
                rc = 1
            drill = r.get("swap_drill")
            if drill is not None:
                if drill.get("params_version") is None:
                    # Publish thread raised (recorded in drill["error"]) or
                    # never finished — the drill FAILED, not the loadgen.
                    print(f"FAIL[{arm}]: hot-swap publish did not complete: "
                          f"{drill.get('error', 'publish thread hung')}",
                          file=sys.stderr)
                    rc = 1
                else:
                    print(f"[{arm}] swap drill: published "
                          f"v{drill['params_version']} "
                          f"in {drill.get('publish_s')}s with "
                          f"{drill['inflight_at_swap']} in flight -> "
                          f"dropped {drill['dropped']}")
                if drill["dropped"] > 0:
                    print(f"FAIL[{arm}]: hot-swap dropped queries",
                          file=sys.stderr)
                    rc = 1
            burn = r.get("burn_drill")
            if burn is not None:
                got_capture = any(
                    c.get("flight_dump") or c.get("span_snapshot")
                    for c in burn["captures"].values()
                )
                print(f"[{arm}] burn drill: tripped={burn['tripped']} "
                      f"fast_events={burn['fast_burn_events']} "
                      f"once_latched={burn['once_latched']} "
                      f"captures={len(burn['captures'])}")
                if not (burn["tripped"] and burn["fast_burn_events"] >= 1
                        and burn["once_latched"] and got_capture):
                    print(f"FAIL[{arm}]: burn drill did not trip/latch/"
                          f"capture as required", file=sys.stderr)
                    rc = 1

        drift_drill_result = None
        if args.drift_drill:
            drill = run_drift_drill(args, ckpt, logger, recorder, capture)
            drift_drill_result = drill
            got_capture = any(
                c.get("flight_dump") or c.get("span_snapshot")
                for c in drill.get("captures", {}).values()
            )
            ok = (
                drill.get("tripped")
                and drill.get("critical_events", 0) >= 1
                and drill.get("once_latched")
                and got_capture
                and drill.get("rearmed_on_publish")
                and drill.get("rebaselined")
                and drill.get("clean_after_publish")
            )
            print(f"[drift drill] calibrated floor "
                  f"{drill['calibration']['threshold']} "
                  f"(clean_frac {drill['calibration']['clean_frac']}) -> "
                  f"tripped={drill.get('tripped')} "
                  f"after={drill.get('tripped_after')} shifted queries, "
                  f"features={drill.get('drift_features')}, "
                  f"once_latched={drill.get('once_latched')}, "
                  f"publish_rearm={drill.get('rearmed_on_publish')}, "
                  f"clean_after={drill.get('clean_after_publish')}")
            if not ok:
                print("FAIL[drift drill]: did not trip/latch/capture/"
                      "re-arm as required", file=sys.stderr)
                rc = 1

        chaos_drill_result = None
        if args.chaos_drill:
            drill = run_chaos_drill(args, ckpt, logger, recorder, capture)
            chaos_drill_result = drill
            ok = check_chaos_drill(drill)
            rb = drill.get("rollback", {})
            print(f"[chaos drill] breaker: opened={drill.get('breaker_opened')} "
                  f"criticals={drill.get('breaker_open_criticals')} "
                  f"recovered={drill.get('breaker_recovered')}; "
                  f"rollback: refused={rb.get('poisoned_publish_refused')} "
                  f"dropped={rb.get('dropped_during_rollback')} "
                  f"recompiles={rb.get('steady_recompiles')}; "
                  f"rearm: drift={drill.get('drift_rearmed')} "
                  f"slo={drill.get('slo_rearmed')} "
                  f"rollback_latch={drill.get('rollback_latch_rearmed')}; "
                  f"ckpt: fallback_step={drill.get('ckpt', {}).get('fallback_step')} "
                  f"bitwise={drill.get('ckpt', {}).get('bitwise_equal')}")
            if not ok:
                print("FAIL[chaos drill]: containment did not hold as "
                      "required", file=sys.stderr)
                rc = 1
            if args.chaos_artifact:
                artifact = {
                    "config": {
                        "tenants": args.tenants, "N": args.N, "K": args.K,
                        "device": args.device, "seed": args.seed,
                        "threshold": drill.get("threshold"),
                        "open_s": drill.get("open_s"),
                    },
                    "chaos_drill": drill,
                    "passed": ok,
                    # The zero-bands tools/bench_trend.py folds: a
                    # containment regression (a dropped request during
                    # rollback, a steady-state recompile) fails --check.
                    "zero_bands": {
                        "dropped_during_rollback":
                            rb.get("dropped_during_rollback"),
                        "steady_recompiles": rb.get("steady_recompiles"),
                    },
                }
                with open(args.chaos_artifact, "w") as f:
                    json.dump(artifact, f, indent=1)
                print(f"wrote {args.chaos_artifact}", file=sys.stderr)

        report = {
            "config": {
                "tenants": args.tenants, "N": args.N, "K": args.K,
                "buckets": args.buckets, "queue_depth": args.queue_depth,
                "tenant_share": args.tenant_share,
                "rate": args.rate, "concurrency": args.concurrency,
                "duration": args.duration, "device": args.device,
                "serving_dp": args.serving_dp, "seed": args.seed,
                "swap_drill": bool(args.swap_drill),
                "trace_sample": args.trace_sample,
                "burn_drill": bool(args.burn_drill),
                "drift_drill": bool(args.drift_drill),
                "chaos_drill": bool(args.chaos_drill),
                "slo_latency_ms": args.slo_latency_ms,
                "slo_availability": args.slo_availability,
            },
            "arms": results,
        }
        if drift_drill_result is not None:
            report["drift_drill"] = drift_drill_result
        if chaos_drill_result is not None:
            report["chaos_drill"] = chaos_drill_result
        if "continuous" in results and "microbatch" in results:
            c, m = results["continuous"], results["microbatch"]
            comparison = {}
            if "closed" in c and "closed" in m:
                comparison["closed_qps_continuous"] = c["closed"]["qps"]
                comparison["closed_qps_microbatch"] = m["closed"]["qps"]
                comparison["closed_qps_ratio"] = round(
                    c["closed"]["qps"] / max(m["closed"]["qps"], 1e-9), 3
                )
            if "open" in c and "open" in m:
                comparison["open_p99_continuous_ms"] = c["open"]["p99_ms"]
                comparison["open_p99_microbatch_ms"] = m["open"]["p99_ms"]
                if m["open"]["p99_ms"]:
                    comparison["open_p99_ratio"] = round(
                        c["open"]["p99_ms"] / m["open"]["p99_ms"], 3
                    )
            report["comparison"] = comparison
            print("A/B: " + json.dumps(comparison))

        print(json.dumps(report))
        if args.artifact:
            with open(args.artifact, "w") as f:
                json.dump(report, f, indent=1)
            print(f"wrote {args.artifact}", file=sys.stderr)
        if args.run_dir:
            print(f"telemetry in {args.run_dir} — render with "
                  f"'python tools/obs_report.py {args.run_dir}'",
                  file=sys.stderr)
        return rc
    finally:
        if capture is not None:
            # Join an in-flight background profiler capture: letting the
            # interpreter tear down around the profiler's C++ session
            # segfaulted at exit.
            capture.wait(10.0)
        if logger is not None:
            logger.close()
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main())
