#!/usr/bin/env python3
"""Serving load generator: closed- and open-loop traffic against the
inference engine, reporting a throughput/latency table.

The acceptance demo for serving/ (ISSUE 1): on CPU against a synthetic-data
checkpoint it must show ZERO recompiles after warmup (the query path
compiles at most one program per shape bucket) and print p50/p99 latency +
throughput; it also verifies registry-based scoring matches the direct
episodic forward pass to numerical tolerance before generating load.

* closed loop: C workers, each submitting synchronously — throughput is
  latency-bound, the classic "how fast can N clients go" number.
* open loop: Poisson arrivals at a fixed offered rate — latency under a
  load the clients do NOT adapt to, where queueing/backpressure shows up.

Usage:
    python tools/loadgen.py [--ckpt DIR] [--mode closed|open|both]
        [--concurrency 4] [--rate 200] [--duration 5] [--N 5] [--K 5]

No --ckpt: a synthetic-data checkpoint is created in a temp dir (fresh-init
weights saved + restored through the real CheckpointManager read path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--ckpt", default=None,
                   help="checkpoint dir to serve (default: build a "
                        "synthetic-data checkpoint in a temp dir)")
    p.add_argument("--mode", default="both", choices=["closed", "open", "both"])
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop client threads")
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop offered rate (queries/s)")
    p.add_argument("--duration", type=float, default=5.0,
                   help="seconds per load phase")
    p.add_argument("--N", type=int, default=5, help="registered classes")
    p.add_argument("--K", type=int, default=5, help="shots per class")
    p.add_argument("--na_rate", type=int, default=0,
                   help="train-config NOTA rate for the synthetic checkpoint "
                        "(>0 builds the no-relation head)")
    p.add_argument("--buckets", default="1,2,4,8,16")
    p.add_argument("--queue_depth", type=int, default=64)
    p.add_argument("--deadline_ms", type=float, default=1000.0)
    p.add_argument("--batch_window_ms", type=float, default=2.0)
    p.add_argument("--device", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def make_synthetic_checkpoint(args, tmpdir: str) -> str:
    """Fresh-init induction weights saved through the real CheckpointManager
    (so the engine exercises the genuine restore path)."""
    import jax
    import numpy as np

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import make_synthetic_glove
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.train.checkpoint import CheckpointManager
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = ExperimentConfig(
        device=args.device, n=args.N, train_n=args.N, k=args.K,
        na_rate=args.na_rate, vocab_size=2002, seed=args.seed,
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch

    model = build_model(cfg, glove_init=vocab.vectors)
    state = init_state(model, cfg,
                       zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
                       zero_batch(cfg.max_length, (1, cfg.total_q)),
                       rng=jax.random.key(cfg.seed))
    ckpt = os.path.join(tmpdir, "ckpt")
    mngr = CheckpointManager(ckpt, cfg, stage="off")
    try:
        mngr.save(0, state, val_accuracy=0.0)
        mngr.wait()
    finally:
        mngr.close()
    return ckpt


def check_registry_parity(engine, ds) -> float:
    """Registry scoring vs the direct episodic forward pass: one episode of
    the registered supports + held-out queries through BOTH paths."""
    import numpy as np

    from induction_network_on_fewrel_tpu.serving.buckets import QUERY_DTYPES

    k, names = engine.registry.k, list(engine.class_names)
    tok = engine.tokenizer

    def stack(insts, lead):
        toks = [tok(i) for i in insts]
        return {
            key: np.stack([getattr(t, key) for t in toks])
            .astype(dt).reshape((1,) + lead + (-1,))
            for key, dt in QUERY_DTYPES.items()
        }

    sup = stack(
        [i for r in names for i in (list(ds.instances[r]) * k)[:k]],
        (len(names), k),
    )
    qry = stack([ds.instances[r][-1] for r in names], (len(names),))
    direct = np.asarray(engine.model.apply(engine.params, sup, qry))[0]
    # The served side pads to a real shape bucket (exactly what the batcher
    # does), so this check reuses warmed programs instead of compiling a
    # one-off shape that would trip the steady-recompile counter.
    from induction_network_on_fewrel_tpu.serving.buckets import (
        pad_rows,
        select_bucket,
    )

    bucket = select_bucket(len(names), engine.batcher.buckets)
    served = engine.programs.run(
        engine.params, engine.registry.class_matrix(),
        {key: pad_rows(qry[key][0], bucket) for key in qry},
    )[: len(names)]
    return float(np.max(np.abs(direct - served)))


def run_closed(engine, pool, concurrency, duration, rng):
    lat, errs = [], [0]
    stop = time.monotonic() + duration
    lock = threading.Lock()

    def worker(seed):
        import numpy as np

        r = np.random.default_rng(seed)
        mine = []
        while time.monotonic() < stop:
            inst = pool[int(r.integers(len(pool)))]
            t0 = time.monotonic()
            try:
                engine.classify(inst)
                mine.append(time.monotonic() - t0)
            except Exception:  # noqa: BLE001 — counted, load continues
                with lock:
                    errs[0] += 1
        with lock:
            lat.extend(mine)

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(concurrency)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    return lat, errs[0], wall


def run_open(engine, pool, rate, duration, rng):
    """Poisson arrivals at ``rate``/s; non-adaptive (futures collected at
    the end) — saturation surfaces as Saturated rejections + p99 growth."""
    futures, lat, rejected = [], [], 0
    stop = time.monotonic() + duration
    next_t = time.monotonic()
    i = 0
    while time.monotonic() < stop:
        now = time.monotonic()
        if now < next_t:
            time.sleep(min(next_t - now, 0.01))
            continue
        next_t += rng.exponential(1.0 / rate)
        inst = pool[int(rng.integers(len(pool)))]
        t0 = time.monotonic()
        try:
            futures.append((t0, engine.submit(inst)))
        except Exception:  # noqa: BLE001 — Saturated backpressure
            rejected += 1
        i += 1
    t_end = time.monotonic()
    deadline_miss = 0
    for t0, fut in futures:
        try:
            # The verdict's own latency_ms (enqueue -> verdict), not the
            # time of this post-hoc result() call — futures resolve while
            # the arrival loop is still generating.
            lat.append(fut.result(timeout=30.0)["latency_ms"] / 1e3)
        except Exception:  # noqa: BLE001 — DeadlineExceeded etc.
            deadline_miss += 1
    wall = t_end - (stop - duration)
    return lat, rejected, deadline_miss, wall, i


def pct(lat, q):
    if not lat:
        return float("nan")
    s = sorted(lat)
    return s[min(len(s) - 1, max(0, int(round(q / 100 * len(s))) - 1))] * 1e3


def main() -> int:
    args = parse_args()
    import numpy as np

    from induction_network_on_fewrel_tpu.cli import select_device
    from induction_network_on_fewrel_tpu.config import ExperimentConfig

    select_device(ExperimentConfig(device=args.device), "auto")

    from induction_network_on_fewrel_tpu.data import make_synthetic_fewrel
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine

    rng = np.random.default_rng(args.seed)
    tmp = None
    ckpt = args.ckpt
    if ckpt is None:
        tmp = tempfile.TemporaryDirectory(prefix="loadgen_")
        print("building synthetic-data checkpoint...", file=sys.stderr)
        ckpt = make_synthetic_checkpoint(args, tmp.name)

    engine = InferenceEngine.from_checkpoint(
        ckpt, device=args.device, k=args.K,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        max_queue_depth=args.queue_depth,
        batch_window_s=args.batch_window_ms / 1e3,
        default_deadline_s=args.deadline_ms / 1e3,
    )
    try:
        ds = make_synthetic_fewrel(
            num_relations=args.N, instances_per_relation=args.K + 10,
            vocab_size=2000, seed=args.seed,
        )
        engine.register_dataset(ds)
        compiled = engine.warmup()
        print(f"warmup: {compiled} bucket programs "
              f"(buckets={list(engine.batcher.buckets)})", file=sys.stderr)

        delta = check_registry_parity(engine, ds)
        print(f"registry vs direct forward: max|delta| = {delta:.2e}",
              file=sys.stderr)
        if not delta < 1e-4:
            print("FAIL: registry parity out of tolerance", file=sys.stderr)
            return 1

        pool = [
            inst for r in ds.rel_names for inst in ds.instances[r][args.K:]
        ]
        rows = []
        if args.mode in ("closed", "both"):
            lat, errs, wall = run_closed(
                engine, pool, args.concurrency, args.duration, rng
            )
            rows.append({
                "mode": f"closed c={args.concurrency}",
                "offered_qps": "-",
                "qps": round(len(lat) / wall, 1),
                "p50_ms": round(pct(lat, 50), 2),
                "p99_ms": round(pct(lat, 99), 2),
                "rejected": errs, "deadline_miss": 0,
            })
        if args.mode in ("open", "both"):
            lat, rej, miss, wall, offered = run_open(
                engine, pool, args.rate, args.duration, rng
            )
            rows.append({
                "mode": f"open r={args.rate:g}/s",
                "offered_qps": round(offered / wall, 1),
                "qps": round(len(lat) / wall, 1),
                "p50_ms": round(pct(lat, 50), 2),
                "p99_ms": round(pct(lat, 99), 2),
                "rejected": rej, "deadline_miss": miss,
            })

        snap = engine.stats.snapshot(queue_depth=engine.batcher.queue_depth)
        hdr = ("mode", "offered_qps", "qps", "p50_ms", "p99_ms",
               "rejected", "deadline_miss")
        widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in hdr]
        print("  ".join(h.ljust(w) for h, w in zip(hdr, widths)))
        for r in rows:
            print("  ".join(str(r[h]).ljust(w) for h, w in zip(hdr, widths)))
        print(f"batch occupancy: {snap['batch_occupancy']:.2f}  "
              f"batches: {snap['batches']}  served: {snap['served']}")
        print(f"recompiles after warmup: {snap['steady_recompiles']} "
              f"(warmup compiled {snap['warmup_compiles']})")
        print(json.dumps({"parity_max_delta": delta, **snap,
                          "rows": rows}))
        if snap["steady_recompiles"] > 0:
            print("FAIL: query path recompiled after warmup", file=sys.stderr)
            return 1
        return 0
    finally:
        engine.close()
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main())
