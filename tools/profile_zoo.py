#!/usr/bin/env python3
"""Profile one zoo-outlier config and rank its device ops (round-3 VERDICT
weak item 6: back the "architecture-inherent" explanation for the gnn /
snail / BERT-PAIR throughput outliers with a trace instead of prose).

Usage: python tools/profile_zoo.py {gnn|snail|pair} [--top 20]

Reuses bench_sweep's prepare_config so the traced program IS the sweep
row's program; prints the top device ops for the traced fused call plus
the analytic MFU at the measured rate (utils/flops.train_step_flops).
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _collapse(name: str) -> str:
    while True:
        stripped = re.sub(r"\.\d+$", "", name)
        if stripped == name:
            return name
        name = stripped


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("model", choices=["gnn", "snail", "pair", "cnn1shot"])
    ap.add_argument("--top", type=int, default=20)
    args = ap.parse_args()

    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.utils.flops import (
        peak_flops_per_chip,
        train_step_flops,
    )
    from bench_sweep import prepare_config

    base = dict(batch_size=8, max_length=40, vocab_size=2002,
                compute_dtype="bfloat16")
    if args.model == "pair":
        cfg = ExperimentConfig(
            encoder="bert", model="pair", n=5, k=5, q=5,
            **{**base, "batch_size": 1, "steps_per_call": 2},
        )
    elif args.model == "cnn1shot":
        # The CNN cached headline (sweep row 1t, round-5 VERDICT item 5b):
        # 5w1s induction on the token-cache fused path — the highest
        # eps/s row in the sweep at the lowest MFU; this trace answers
        # whether the bound is gathers/dispatch or something fixable.
        cfg = ExperimentConfig(
            encoder="cnn", n=5, k=1, q=5, token_cache=True,
            steps_per_call=512, **base,
        )
    else:
        cfg = ExperimentConfig(
            encoder="cnn", model=args.model, n=5, k=5, q=5, token_cache=True,
            steps_per_call=64, **base,
        )
    p = prepare_config(f"profile:{args.model}", cfg)

    t0 = time.monotonic()
    for _ in range(3):
        p["pack"], metrics = p["step_once"](p["pack"])
    loss = metrics["loss"]
    import numpy as np

    _ = float(np.ravel(jax.device_get(loss))[-1])
    print(f"warmup(+compile) {time.monotonic() - t0:.1f}s", file=sys.stderr)

    tmpdir = tempfile.mkdtemp(prefix=f"profile_{args.model}_")
    jax.profiler.start_trace(tmpdir)
    t0 = time.monotonic()
    p["pack"], metrics = p["step_once"](p["pack"])
    _ = float(np.ravel(jax.device_get(metrics["loss"]))[-1])
    wall = time.monotonic() - t0
    jax.profiler.stop_trace()
    eps = p["eff"] * cfg.batch_size / wall
    flops = train_step_flops(cfg)["per_episode"]
    peak = peak_flops_per_chip(jax.devices()[0].device_kind, cfg.compute_dtype)
    mfu = eps * flops / peak if peak else None
    print(
        f"traced call: {wall:.3f}s -> {eps:.0f} eps/s/chip; analytic "
        f"{flops / 1e9:.2f} GFLOP/episode -> mfu "
        + (f"{mfu:.3f}" if mfu is not None else "n/a")
    )

    files = glob.glob(tmpdir + "/**/*.xplane.pb", recursive=True)
    data = jax.profiler.ProfileData.from_file(files[0])
    for plane in data.planes:
        if "/device:" not in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            per_op: dict[str, tuple[float, int]] = {}
            total = 0
            for e in line.events:
                name = _collapse(e.name)
                ns, cnt = per_op.get(name, (0.0, 0))
                per_op[name] = (ns + e.duration_ns, cnt + 1)
                total += e.duration_ns
            if not per_op:
                continue
            print(f"\n== {plane.name} / XLA Ops, total {total / 1e6:.1f} ms")
            for name, (ns, cnt) in sorted(
                per_op.items(), key=lambda kv: -kv[1][0]
            )[: args.top]:
                print(
                    f"  {ns / 1e6:9.2f} ms {cnt:6d}x {100 * ns / total:5.1f}%  "
                    f"{name[:160]}"
                )
    for c in p["closers"]:
        c.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
