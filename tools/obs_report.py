#!/usr/bin/env python3
"""Render one run report from the telemetry stream (ISSUE 2 tentpole §4).

Input: a run dir holding ``metrics.jsonl`` (always written by training and
serving), plus — when present — ``flight_recorder.json`` (obs/recorder.py)
and ``config.json`` (checkpoint dir; enables analytic MFU).

Modes:

* default        — human-readable report: p50/p99 step time, episodes/sec
                   trend, MFU (when the chip is known), eval accuracy ± CI,
                   serving percentiles, request-trace waterfalls (sampled
                   kind="trace" records; segment sums checked within 5% of
                   measured latency), per-tenant SLO burn events, the
                   prediction-quality table + drift state (kind="quality",
                   ISSUE 10), scenario-harness legs (kind="scenario"),
                   step-time decomposition + compile forensics
                   (kind="perf"/"compile", ISSUE 11: segment fractions,
                   tile check, out-of-band causes, compile phases),
                   health events, flight-recorder summary. Always
                   schema-checks first; a malformed stream is a finding,
                   not a crash.
* ``--check``    — schema validation only; exit 1 on any violation. This
                   is the machine gate tier-1 runs (tests/test_obs.py).
* ``--json``     — the report as one JSON object (for dashboards/CI).
* ``--overhead`` — measure span enter/exit cost with a timed_call A/B and
                   state it as a fraction of the run's own p50 step time
                   (acceptance: < 2% on the headline config).

Usage:
    python tools/obs_report.py RUN_DIR [--check] [--json] [--overhead]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from induction_network_on_fewrel_tpu.utils.metrics import KNOWN_KINDS  # noqa: E402
# ONE home for the tiled-segment list (obs/perf.py): a segment added
# there must be summed here, or tiles_ok_frac reports a false violation.
from induction_network_on_fewrel_tpu.obs.perf import (  # noqa: E402
    TILE_SEGMENTS as PERF_SEGMENTS,
)


# --- schema check ---------------------------------------------------------

def check_schema(path: Path, max_errors: int = 20) -> tuple[int, list[str]]:
    """Validate metrics.jsonl: one JSON object per line with step (int),
    kind (known), wall_s (number), and scalar (number/str) fields.
    Returns (record_count, errors)."""
    errors: list[str] = []
    n = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            if len(errors) >= max_errors:
                errors.append("... (further errors suppressed)")
                break
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {lineno}: not JSON ({e.msg})")
                continue
            if not isinstance(rec, dict):
                errors.append(f"line {lineno}: not a JSON object")
                continue
            n += 1
            step = rec.get("step")
            if not isinstance(step, int) or isinstance(step, bool):
                errors.append(f"line {lineno}: step must be an int, got {step!r}")
            kind = rec.get("kind")
            if kind not in KNOWN_KINDS:
                errors.append(
                    f"line {lineno}: unknown kind {kind!r} "
                    f"(known: {sorted(KNOWN_KINDS)})"
                )
            if not isinstance(rec.get("wall_s"), (int, float)):
                errors.append(f"line {lineno}: wall_s must be a number")
            for k, v in rec.items():
                if k in ("step", "kind", "wall_s"):
                    continue
                if not isinstance(v, (int, float, str)):
                    errors.append(
                        f"line {lineno}: field {k!r} must be scalar/str, "
                        f"got {type(v).__name__}"
                    )
    return n, errors


# --- aggregation ----------------------------------------------------------

def _percentile(xs: list[float], q: float) -> float:
    """Nearest-rank percentile (same convention as serving/stats.py)."""
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(round(q / 100.0 * len(s))) - 1))
    return s[i]


def load_records(path: Path) -> list[dict]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # counted by check_schema; aggregation skips
    return recs


def _process_identity(recs: list[dict]) -> str | None:
    """The per-process identity column (ISSUE 17): multi-process fleet
    streams stamp proc_role/proc_replica/proc_pid on every record
    (utils/metrics.MetricsLogger.set_identity). Folded to one string per
    distinct process so single-process runs (no identity set) render
    exactly as before — the column only appears when the stream carries
    it."""
    seen: dict[tuple, None] = {}
    for r in recs:
        role = r.get("proc_role")
        if not isinstance(role, str):
            continue
        key = (role, r.get("proc_replica"), r.get("proc_pid"))
        seen.setdefault(key, None)
    if not seen:
        return None
    parts = []
    for role, replica, pid in seen:
        tag = f"{role}/{replica}" if isinstance(replica, str) else role
        parts.append(f"{tag} pid={int(pid)}" if isinstance(
            pid, (int, float)) else tag)
    return ", ".join(parts)


def train_summary(recs: list[dict]) -> dict | None:
    """Per-window step times from consecutive train records: each record
    logs at wall_s having advanced `step`; dt/dstep is the honest
    per-step wall time for that window (includes host feed + dispatch)."""
    train = [r for r in recs if r.get("kind") == "train"]
    if not train:
        return None
    step_times, eps = [], []
    for prev, cur in zip(train, train[1:]):
        dstep = cur.get("step", 0) - prev.get("step", 0)
        dwall = cur.get("wall_s", 0.0) - prev.get("wall_s", 0.0)
        if dstep > 0 and dwall > 0:
            step_times.append(dwall / dstep)
    eps = [
        float(r["episodes_per_s"]) for r in train
        if isinstance(r.get("episodes_per_s"), (int, float))
        and math.isfinite(r["episodes_per_s"])
    ]
    out = {
        "records": len(train),
        "first_step": train[0].get("step"),
        "last_step": train[-1].get("step"),
    }
    if step_times:
        out["step_time_p50_s"] = round(_percentile(step_times, 50), 6)
        out["step_time_p99_s"] = round(_percentile(step_times, 99), 6)
    if eps:
        out["eps_mean"] = round(sum(eps) / len(eps), 2)
        out["eps_min"] = round(min(eps), 2)
        out["eps_max"] = round(max(eps), 2)
        half = len(eps) // 2
        if half:
            first = sum(eps[:half]) / half
            second = sum(eps[half:]) / (len(eps) - half)
            out["eps_trend"] = round(second / first, 4) if first > 0 else None
    losses = [
        r["loss"] for r in train
        if isinstance(r.get("loss"), (int, float)) and math.isfinite(r["loss"])
    ]
    if losses:
        out["loss_first"] = round(losses[0], 6)
        out["loss_last"] = round(losses[-1], 6)
    return out


def eval_summary(recs: list[dict]) -> dict | None:
    evals = [r for r in recs if r.get("kind") in ("val", "eval", "test")]
    if not evals:
        return None
    last = evals[-1]
    out = {"records": len(evals), "last_step": last.get("step")}
    for k in ("accuracy", "acc_ci95", "nota_precision", "nota_recall"):
        if isinstance(last.get(k), (int, float)):
            out[k] = round(last[k], 4)
    return out


def serve_summary(recs: list[dict]) -> dict | None:
    """Serving section (ISSUE 7 fleet upgrade): the aggregate stream is
    the records WITHOUT a ``tenant`` field; per-tenant records restate the
    counters tenant-by-tenant (one kind="serve" record per tenant per emit
    — serving/stats.ServingStats.emit), and ``event`` records mark
    control-plane actions (hot-swap publishes). The section renders the
    aggregate headline, a per-tenant p50/p99 table, and shed/swap event
    counts."""
    serves = [r for r in recs if r.get("kind") == "serve"]
    if not serves:
        return None
    events = [r for r in serves if isinstance(r.get("event"), str)]
    tenant_recs = [
        r for r in serves
        if isinstance(r.get("tenant"), str) and not isinstance(
            r.get("event"), str
        )
    ]
    aggregate = [
        r for r in serves
        if not isinstance(r.get("event"), str)
        and not isinstance(r.get("tenant"), str)
    ]
    out: dict = {"records": len(serves)}
    proc = _process_identity(serves)
    if proc:
        out["process"] = proc
    if aggregate:
        last = aggregate[-1]
        out.update({
            k: last[k] for k in (
                "served", "rejected", "shed", "deadline_missed", "batches",
                "batch_occupancy", "p50_ms", "p99_ms", "queue_depth",
                "steady_recompiles", "swaps",
            ) if k in last
        })
    if tenant_recs:
        # Last record per tenant is that tenant's current counters.
        by_tenant: dict[str, dict] = {}
        for r in tenant_recs:
            by_tenant[r["tenant"]] = {
                k: r[k] for k in (
                    "served", "rejected", "shed", "deadline_missed",
                    "p50_ms", "p99_ms",
                ) if k in r
            }
        out["tenants"] = {t: by_tenant[t] for t in sorted(by_tenant)}
    swaps = [r for r in events if r.get("event") == "snapshot_swap"]
    if swaps:
        out["swap_events"] = len(swaps)
        last_swap = swaps[-1]
        if isinstance(last_swap.get("params_version"), (int, float)):
            out["params_version"] = int(last_swap["params_version"])
    return out


def ckpt_summary(recs: list[dict]) -> dict | None:
    """Ring-save telemetry (round 6, kind="ckpt"): how many boundary saves
    ran in each mode and what the steady-state payload is — the delta-ring
    byte diet, read straight off the stream."""
    saves = [
        r for r in recs
        if r.get("kind") == "ckpt" and r.get("event") == "ring_save"
    ]
    if not saves:
        return None
    by_mode: dict[str, int] = {}
    for s in saves:
        by_mode[str(s.get("mode"))] = by_mode.get(str(s.get("mode")), 0) + 1
    out = {"records": len(saves), "by_mode": by_mode}
    last = saves[-1]
    if isinstance(last.get("bytes"), (int, float)):
        out["last_bytes"] = int(last["bytes"])
        out["last_mode"] = last.get("mode")
    deltas = [
        s["bytes"] for s in saves
        if s.get("mode") == "delta" and isinstance(s.get("bytes"), (int, float))
    ]
    fulls = [
        s["bytes"] for s in saves
        if s.get("mode") in ("full", "base")
        and isinstance(s.get("bytes"), (int, float))
    ]
    if deltas:
        out["delta_bytes_mean"] = int(sum(deltas) / len(deltas))
    if deltas and fulls:
        # The headline ratio: steady-state delta payload vs a full save.
        out["delta_over_full"] = round(
            (sum(deltas) / len(deltas)) / max(fulls), 4
        )
    rows = [
        s["rows"] for s in saves
        if isinstance(s.get("rows"), (int, float))
    ]
    if rows:
        out["rows_last"] = int(rows[-1])
    return out


def data_summary(recs: list[dict]) -> dict | None:
    """Input-pipeline section (ISSUE 4, kind="data"): the headline is the
    feed stall fraction — consumer seconds blocked on the queue over the
    windows' wall seconds (acceptance: < 2% of step time with prefetch
    enabled). Window records carry window_s; stall ticks (emitted while
    blocked) carry stalled_s and no window_s — they contribute context
    (producer liveness, poison counts) but not the fraction's denominator."""
    data = [r for r in recs if r.get("kind") == "data"]
    if not data:
        return None
    windows = [
        r for r in data
        if isinstance(r.get("window_s"), (int, float)) and r["window_s"] > 0
    ]
    out = {"records": len(data), "windows": len(windows)}
    if windows:
        stall = sum(float(r.get("stall_s", 0.0)) for r in windows)
        wall = sum(float(r["window_s"]) for r in windows)
        produce = sum(float(r.get("produce_s", 0.0)) for r in windows)
        out["stall_s_total"] = round(stall, 4)
        out["produce_s_total"] = round(produce, 4)
        out["feed_stall_frac"] = round(stall / wall, 6) if wall > 0 else None
        depths = [
            float(r["queue_depth"]) for r in windows
            if isinstance(r.get("queue_depth"), (int, float))
        ]
        if depths:
            out["queue_depth_mean"] = round(sum(depths) / len(depths), 3)
    last = data[-1]
    for k in ("produced", "consumed", "queue_depth", "episodes_buffered",
              "producer_alive", "poisoned"):
        if isinstance(last.get(k), (int, float)):
            out[k] = last[k]
    stalls = [r for r in data if "stalled_s" in r]
    if stalls:
        out["stall_ticks"] = len(stalls)
        out["longest_stall_s"] = round(
            max(float(r.get("stalled_s", 0.0)) for r in stalls), 3
        )
    return out


def comms_summary(recs: list[dict]) -> dict | None:
    """Collective-traffic section (ISSUE 5, kind="comms"): the headline is
    wire_mb_per_step — bytes every training step puts on the ICI fabric
    per device, from the ledger arithmetic the compiled HLO is asserted
    against (utils/roofline.comms_components / tools/comms_ledger.py).
    The records are per-window restatements of a per-step constant, so
    the LAST record is the truth; a mid-run change (it would take a
    restart with different dp/compact_demb) would show in the count."""
    comms = [r for r in recs if r.get("kind") == "comms"]
    if not comms:
        return None
    last = comms[-1]
    out = {"records": len(comms)}
    for k in ("wire_mb_per_step", "payload_bytes_per_step",
              "wire_bytes_per_step", "dp", "compact_demb", "demb_u_rows"):
        if isinstance(last.get(k), (int, float)):
            out[k] = last[k]
    return out


def roofline_summary(recs: list[dict], run_dir: Path) -> dict | None:
    """HBM-roofline section (ISSUE 6, kind="roofline"): the headline is
    step_mb — analytic HBM bytes per train step at this config's residual
    knobs, from the shared arithmetic bench.py stamps and the tier-1 gate
    holds to ROOFLINE_r*.json (utils/roofline.step_bytes). Per-window
    restatements of a per-step constant, so the LAST record is the truth.
    When the run dir carries a config.json the per-component byte table
    is rebuilt from the same formulas (the full roofline-ledger view)."""
    rl = [r for r in recs if r.get("kind") == "roofline"]
    if not rl:
        return None
    last = rl[-1]
    out = {"records": len(rl)}
    for k in ("step_mb", "step_bytes", "lstm_residual_bytes",
              "lstm_cs_window", "corpus_rows"):
        if isinstance(last.get(k), (int, float)):
            out[k] = last[k]
    cfg_path = run_dir / "config.json"
    if cfg_path.exists():
        try:
            from induction_network_on_fewrel_tpu.config import (
                ExperimentConfig,
            )
            from induction_network_on_fewrel_tpu.utils.roofline import (
                step_components,
            )

            cfg = ExperimentConfig.from_json(cfg_path.read_text())
            # Same corpus bound as the headline (the record carries it on
            # real-corpus lazy runs) — else the table's demb/optimizer
            # rows fall back to the synthetic default and stop summing to
            # step_mb.
            u_rows = last.get("corpus_rows")
            out["components_mb"] = {
                name: round(b / 1e6, 1)
                for name, b, _ in step_components(
                    cfg,
                    corpus_rows=int(u_rows) if u_rows else None,
                )
            }
        except Exception as e:  # table is best-effort; headline stands
            out["components_error"] = f"{type(e).__name__}: {e}"
    return out




def perf_summary(recs: list[dict]) -> dict | None:
    """Step-time decomposition section (ISSUE 11, kind="perf"): per-window
    segments that tile the measured window (obs/perf.py). Headlines: the
    median segment fractions (where the wall time goes), the tile check
    (fraction of windows whose segments sum to window_s within 5% — the
    acceptance bar; by construction it should be 1.0), out-of-band window
    count and the cause table, and the roofline-floor comparison when the
    stream carries it."""
    perf = [
        r for r in recs
        if r.get("kind") == "perf"
        and isinstance(r.get("window_s"), (int, float))
    ]
    if not perf:
        return None
    out: dict = {"windows": len(perf)}

    def med(key: str) -> float | None:
        xs = [
            float(r[key]) for r in perf
            if isinstance(r.get(key), (int, float))
        ]
        return round(_percentile(xs, 50), 4) if xs else None

    out["step_ms_p50"] = med("step_ms")
    total_ms = sum(float(r["window_s"]) for r in perf) * 1e3
    if total_ms > 0:
        for seg in PERF_SEGMENTS:
            seg_ms = sum(float(r.get(f"{seg}_ms", 0.0)) for r in perf)
            out[f"{seg}_frac"] = round(seg_ms / total_ms, 4)
    tiles_ok = sum(
        1 for r in perf
        if abs(
            sum(float(r.get(f"{s}_ms", 0.0)) for s in PERF_SEGMENTS)
            - float(r["window_s"]) * 1e3
        ) <= 0.05 * float(r["window_s"]) * 1e3
    )
    out["tiles_ok_frac"] = round(tiles_ok / len(perf), 4)
    compiles = sum(float(r.get("compiles", 0.0)) for r in perf)
    if compiles:
        out["window_compiles"] = int(compiles)
        out["compile_ms_total"] = round(
            sum(float(r.get("compile_ms", 0.0)) for r in perf), 3
        )
    gc_ms = sum(float(r.get("gc_ms", 0.0)) for r in perf)
    if gc_ms:
        out["gc_ms_total"] = round(gc_ms, 3)
    oob = [r for r in perf if r.get("oob")]
    out["oob_windows"] = len(oob)
    if oob:
        by_cause: dict[str, int] = {}
        for r in oob:
            c = str(r.get("cause"))
            by_cause[c] = by_cause.get(c, 0) + 1
        out["causes"] = by_cause
    floor = med("floor_ms")
    if floor is not None:
        out["floor_ms"] = floor
        out["device_over_floor_p50"] = med("device_over_floor")
    return out


def compile_summary(recs: list[dict]) -> dict | None:
    """Compile-forensics section (ISSUE 11, kind="compile"): one record
    per observed XLA compile (obs/compile.py). Headlines: counts by
    phase (warmup / recompile / dup), total compile seconds, the
    steady-state verdict (any post-arm gated recompile is the invariant
    breach — surfaced via the recompile_burst health event), and the
    slowest compiles with their triggers."""
    comps = [r for r in recs if r.get("kind") == "compile"]
    if not comps:
        return None
    by_phase: dict[str, int] = {}
    for c in comps:
        p = str(c.get("phase"))
        by_phase[p] = by_phase.get(p, 0) + 1
    out: dict = {"records": len(comps), "by_phase": by_phase}
    elapsed = [
        float(c["elapsed_ms"]) for c in comps
        if isinstance(c.get("elapsed_ms"), (int, float))
    ]
    if elapsed:
        out["compile_ms_total"] = round(sum(elapsed), 3)
    bursts = [
        r for r in recs
        if r.get("kind") == "health" and r.get("event") == "recompile_burst"
    ]
    out["recompile_bursts"] = len(bursts)
    slow = sorted(
        (c for c in comps if isinstance(c.get("elapsed_ms"), (int, float))),
        key=lambda c: -float(c["elapsed_ms"]),
    )[:3]
    if slow:
        out["slowest"] = [
            f"{c.get('fn')} {float(c['elapsed_ms']):.1f}ms "
            f"step={c.get('step')} trigger={c.get('trigger')} "
            f"phase={c.get('phase')}"
            for c in slow
        ]
    return out


SEGMENTS = ("queue", "pack", "execute", "respond")


def _waterfall_lines(t: dict, width: int = 32) -> list[str]:
    """One request trace -> ASCII waterfall: each segment drawn at its
    offset within [0, total_ms], so the eye reads WHERE the latency went
    (a long leading gap = queueing; a long tail = device execute)."""
    total = float(t.get("total_ms") or 0.0)
    segs = [(s, float(t.get(f"{s}_ms", 0.0))) for s in SEGMENTS]
    ssum = sum(d for _, d in segs)
    ok = total > 0 and abs(ssum - total) <= 0.05 * total
    head = (
        f"trace {t.get('trace_id')} tenant={t.get('tenant')} "
        f"scheduler={t.get('scheduler')} bucket={int(t.get('bucket', 0))} "
        f"total={total:.3f}ms (segments sum {ssum:.3f}ms, "
        f"{'ok' if ok else 'MISMATCH > 5%'})"
    )
    lines = [head]
    scale = width / total if total > 0 else 0.0
    offset = 0.0
    for name, dur in segs:
        a = int(round(offset * scale))
        b = max(a + 1, int(round((offset + dur) * scale)))
        bar = " " * a + "#" * min(b - a, width - a)
        lines.append(f"  {name:<8}{dur:9.3f}ms |{bar:<{width}}|")
        offset += dur
    return lines


def trace_summary(recs: list[dict]) -> dict | None:
    """Request-scoped tracing section (ISSUE 9, kind="trace"): sampled
    per-request segment records from the serving data plane. Headlines:
    segment medians (which stage owns the latency), the fraction of
    traces whose segments sum to the measured end-to-end latency within
    5% (the tentpole's consistency bar), and a rendered waterfall of the
    slowest sampled request. Control-plane records (op="publish") are
    counted separately."""
    traces = [
        r for r in recs
        if r.get("kind") == "trace"
        and isinstance(r.get("total_ms"), (int, float))
    ]
    control = [r for r in recs if r.get("kind") == "trace" and r.get("op")]
    if not traces and not control:
        return None
    out: dict = {"records": len(traces) + len(control)}
    proc = _process_identity(traces)
    if proc:
        out["process"] = proc
    if traces:
        out["sampled_requests"] = len(traces)

        def med(key: str) -> float | None:
            xs = [
                float(r[key]) for r in traces
                if isinstance(r.get(key), (int, float))
            ]
            return round(_percentile(xs, 50), 3) if xs else None

        for s in SEGMENTS:
            out[f"{s}_ms_p50"] = med(f"{s}_ms")
        out["total_ms_p50"] = med("total_ms")
        sums_ok = sum(
            1 for r in traces
            if r["total_ms"] > 0 and abs(
                sum(float(r.get(f"{s}_ms", 0.0)) for s in SEGMENTS)
                - float(r["total_ms"])
            ) <= 0.05 * float(r["total_ms"])
        )
        out["segments_sum_ok_frac"] = round(sums_ok / len(traces), 4)
        by_tenant: dict[str, int] = {}
        for r in traces:
            tn = str(r.get("tenant"))
            by_tenant[tn] = by_tenant.get(tn, 0) + 1
        out["by_tenant"] = by_tenant
        slowest = max(traces, key=lambda r: float(r["total_ms"]))
        out["waterfall"] = _waterfall_lines(slowest)
    if control:
        out["control_records"] = len(control)
        last = control[-1]
        if isinstance(last.get("publish_ms"), (int, float)):
            out["last_publish_ms"] = last["publish_ms"]
    return out


def slo_summary(recs: list[dict]) -> dict | None:
    """SLO burn-rate section (ISSUE 9): kind="health" events named
    slo_fast_burn / slo_slow_burn, grouped per tenant with the latest
    burn rates — the at-a-glance "who is burning budget" table."""
    events = [
        r for r in recs
        if r.get("kind") == "health"
        and str(r.get("event", "")).startswith("slo_")
    ]
    if not events:
        return None
    out: dict = {"records": len(events)}
    by_tenant: dict[str, dict] = {}
    for e in events:
        tn = str(e.get("tenant"))
        row = by_tenant.setdefault(tn, {"events": 0})
        row["events"] += 1
        row["last_event"] = e.get("event")
        row["severity"] = e.get("severity")
        for k in ("burn_fast", "burn_slow"):
            if isinstance(e.get(k), (int, float)):
                row[k] = e[k]
    out["tenants"] = {t: by_tenant[t] for t in sorted(by_tenant)}
    return out


def quality_summary(recs: list[dict]) -> dict | None:
    """Prediction-quality section (ISSUE 10, kind="quality"): two record
    shapes split on the ``probe`` field — per-tenant TRAFFIC records
    (serving/stats.quality_snapshot: nota_rate / margin_p50 /
    entropy_p50) and DRIFT-STATE records (obs/drift.emit: baseline vs
    current vs band per feature). Headlines: the per-tenant quality
    table, the drift table, and prediction_drift / drift_rearm health
    event counts."""
    quality = [r for r in recs if r.get("kind") == "quality"]
    drift_events = [
        r for r in recs
        if r.get("kind") == "health"
        and r.get("event") in ("prediction_drift", "drift_rearm")
    ]
    if not quality and not drift_events:
        return None
    out: dict = {"records": len(quality)}
    traffic = [r for r in quality if r.get("probe") != "drift"]
    drift = [r for r in quality if r.get("probe") == "drift"]
    if traffic:
        by_tenant: dict[str, dict] = {}
        for r in traffic:
            if isinstance(r.get("tenant"), str):
                by_tenant[r["tenant"]] = {
                    k: r[k] for k in
                    ("served", "nota_rate", "margin_p50", "entropy_p50")
                    if k in r
                }
        if by_tenant:
            out["tenants"] = {t: by_tenant[t] for t in sorted(by_tenant)}
    if drift:
        by_tenant = {}
        for r in drift:
            if isinstance(r.get("tenant"), str):
                by_tenant[r["tenant"]] = {
                    k: r[k] for k in (
                        "window", "latched",
                        "nota_rate_base", "nota_rate_cur", "nota_rate_band",
                        "margin_base", "margin_cur", "margin_band",
                        "entropy_base", "entropy_cur", "entropy_band",
                    ) if k in r
                }
        if by_tenant:
            out["drift"] = {t: by_tenant[t] for t in sorted(by_tenant)}
    drifts = [e for e in drift_events if e.get("event") == "prediction_drift"]
    if drifts:
        out["drift_events"] = len(drifts)
        last = drifts[-1]
        out["last_drift"] = (
            f"{last.get('severity')}: tenant={last.get('tenant')} "
            f"feature={last.get('feature')} "
            f"current={last.get('current')} vs baseline="
            f"{last.get('baseline')} (band {last.get('band')})"
        )
    rearms = [e for e in drift_events if e.get("event") == "drift_rearm"]
    if rearms:
        out["rearms"] = len(rearms)
    return out


def scenario_summary(recs: list[dict]) -> dict | None:
    """Scenario-harness section (ISSUE 10, kind="scenario"): one row per
    evaluated leg from tools/scenarios.py — cross-domain accuracy ± CI,
    the DA-mixture recovery, NOTA calibration best-F1, adversarial
    degradation. The LAST record per leg wins (a re-run supersedes)."""
    scen = [r for r in recs if r.get("kind") == "scenario"]
    if not scen:
        return None
    by_leg: dict[str, dict] = {}
    for r in scen:
        # Distinct legs can share a leg NAME (one cross_domain record per
        # shift, one nota_calibration per na_rate): fold the discriminator
        # into the key so a grid run keeps every row instead of the last.
        leg = str(r.get("leg"))
        if isinstance(r.get("shift"), (int, float)):
            leg = f"{leg}[shift={r['shift']:g}]"
        if isinstance(r.get("na_rate"), (int, float)):
            leg = f"{leg}[na={r['na_rate']:g}]"
        by_leg[leg] = {
            k: r[k] for k in (
                "accuracy", "acc_ci95", "shift", "degradation",
                "best_f1", "best_tau", "na_rate",
                "nota_precision", "nota_recall",
            ) if k in r
        }
    out: dict = {"records": len(scen), "legs": by_leg}
    ind = by_leg.get("in_domain", {}).get("accuracy")
    cross = [
        v["accuracy"] for k, v in by_leg.items()
        if k.startswith("cross_domain")
        and isinstance(v.get("accuracy"), (int, float))
    ]
    if isinstance(ind, (int, float)) and cross:
        # Gap at the WORST shift — the headline degradation.
        out["cross_domain_gap"] = round(ind - min(cross), 4)
    return out


def fault_summary(recs: list[dict]) -> dict | None:
    """Fault-domain section (ISSUE 12, kind="fault"): injections
    (obs/chaos.py, action="inject") next to the containment they
    provoked — checkpoint quarantines, circuit-breaker transitions
    (with each tenant's LAST state), publish rollbacks, degraded-mode
    verdicts. The fault criticals (ckpt_corrupt / breaker_open /
    publish_rollback) appear in the health section; this section is the
    action-level ledger."""
    faults = [r for r in recs if r.get("kind") == "fault"]
    if not faults:
        return None
    by_action: dict[str, int] = {}
    for r in faults:
        a = str(r.get("action"))
        by_action[a] = by_action.get(a, 0) + 1
    out: dict = {"records": len(faults), "by_action": by_action}
    injected = [r for r in faults if r.get("action") == "inject"]
    if injected:
        by_point: dict[str, int] = {}
        for r in injected:
            p = str(r.get("point"))
            by_point[p] = by_point.get(p, 0) + 1
        out["injected_by_point"] = by_point
    quarantines = [r for r in faults if r.get("action") == "ckpt_quarantine"]
    if quarantines:
        out["quarantined_slots"] = [
            f"{q.get('ckpt_kind')}/{int(q.get('ckpt_step', 0))}: "
            f"{q.get('reason')}"
            for q in quarantines[-3:]
        ]
    transitions = [r for r in faults if r.get("action") == "breaker"]
    if transitions:
        last_state: dict[str, str] = {}
        opens = 0
        for r in transitions:
            last_state[str(r.get("tenant"))] = str(r.get("to"))
            opens += r.get("to") == "open"
        out["breaker_opens"] = opens
        out["breaker_last_state"] = dict(sorted(last_state.items()))
    rollbacks = [r for r in faults if r.get("action") == "publish_rollback"]
    if rollbacks:
        out["publish_rollbacks"] = len(rollbacks)
        out["last_rollback"] = str(rollbacks[-1].get("reason"))
    exec_errs = [r for r in faults if r.get("action") == "execute_error"]
    if exec_errs:
        out["execute_error_requests"] = int(sum(
            float(r.get("requests", 0)) for r in exec_errs
        ))
    degraded = [r for r in faults if r.get("action") == "degraded_verdicts"]
    if degraded:
        out["degraded_verdicts"] = int(sum(
            float(r.get("served", 0)) for r in degraded
        ))
    return out


def adapt_summary(recs: list[dict]) -> dict | None:
    """Self-healing adaptation section (ISSUE 14, kind="adapt"): the
    loop outcome table — per tenant: triggers, fine-tunes (ok/failed),
    canary passes/fails, publishes, rollbacks, verified loops, and
    whether the tenant exhausted its retry budget — with the
    time-to-recover headline (the last verified loop's trigger-to-
    back-in-band wall time) and fine-tune/publish costs."""
    adapt = [r for r in recs if r.get("kind") == "adapt"]
    if not adapt:
        return None
    out: dict = {"records": len(adapt)}
    verified = [r for r in adapt if r.get("action") == "verified"]
    if verified:
        out["time_to_recover_s"] = verified[-1].get("recover_s")
        out["verified_loops"] = len(verified)
    trains_ok = [r for r in adapt
                 if r.get("action") == "train" and r.get("ok") == 1.0]
    if trains_ok:
        out["finetune_s_last"] = trains_ok[-1].get("train_s")
    publishes = [r for r in adapt
                 if r.get("action") == "publish" and r.get("ok") == 1.0]
    if publishes:
        out["publish_s_last"] = publishes[-1].get("publish_s")
        out["last_params_version"] = publishes[-1].get("params_version")
    by_tenant: dict[str, dict] = {}
    for r in adapt:
        t = str(r.get("tenant"))
        row = by_tenant.setdefault(t, {
            "triggers": 0, "train_ok": 0, "train_fail": 0,
            "canary_pass": 0, "canary_fail": 0, "publishes": 0,
            "rollbacks": 0, "verified": 0, "exhausted": 0,
        })
        a = r.get("action")
        if a == "trigger":
            row["triggers"] += 1
        elif a == "train":
            row["train_ok" if r.get("ok") == 1.0 else "train_fail"] += 1
        elif a == "canary":
            row["canary_pass" if r.get("passed") == 1.0
                else "canary_fail"] += 1
        elif a == "publish" and r.get("ok") == 1.0:
            row["publishes"] += 1
        elif a == "rollback":
            row["rollbacks"] += 1
        elif a == "verified":
            row["verified"] += 1
        elif a == "exhausted":
            row["exhausted"] += 1
    out["loops"] = {t: by_tenant[t] for t in sorted(by_tenant)}
    exhausted = [r for r in adapt if r.get("action") == "exhausted"]
    if exhausted:
        out["exhausted_tenants"] = sorted(
            {str(r.get("tenant")) for r in exhausted}
        )
    return out


def fleet_summary(recs: list[dict]) -> dict | None:
    """Fleet-tier section (ISSUE 13, kind="fleet"): the router's
    aggregate counters, a per-replica table (state + routed + serving
    percentiles + the per-replica zero-recompile counter), placement
    churn (cumulative ``replaced`` + replace events), and the fan-out
    publish row (publish_s / replicas / params_version of the last
    all-or-nothing fleet publish). Splits the three record shapes on
    the ``replica`` and ``event`` fields — the serve-section
    discipline."""
    fleet = [r for r in recs if r.get("kind") == "fleet"]
    if not fleet:
        return None
    events = [r for r in fleet if isinstance(r.get("event"), str)]
    replica_recs = [
        r for r in fleet
        if isinstance(r.get("replica"), str)
        and not isinstance(r.get("event"), str)
    ]
    aggregate = [
        r for r in fleet
        if not isinstance(r.get("replica"), str)
        and not isinstance(r.get("event"), str)
    ]
    out: dict = {"records": len(fleet)}
    proc = _process_identity(fleet)
    if proc:
        out["process"] = proc
    if aggregate:
        last = aggregate[-1]
        out.update({
            k: last[k] for k in (
                "replicas", "live", "dead", "tenants", "submitted",
                "shed", "degraded_served", "replica_deaths", "replaced",
                "pending_failover",
            ) if k in last
        })
    if replica_recs:
        by_replica: dict[str, dict] = {}
        for r in replica_recs:   # last record per replica wins
            by_replica[r["replica"]] = {
                k: r[k] for k in (
                    "state", "routed", "qps", "served", "p50_ms", "p99_ms",
                    "batch_occupancy", "steady_recompiles", "queue_depth",
                    "breaker",
                ) if k in r
            }
        out["replica_table"] = {
            rid: by_replica[rid] for rid in sorted(by_replica)
        }
    publishes = [e for e in events if e.get("event") == "fanout_publish"]
    if publishes:
        last = publishes[-1]
        out["fanout_publishes"] = len(publishes)
        out["last_fanout"] = {
            k: last[k] for k in ("publish_s", "replicas", "params_version")
            if k in last
        }
    replaces = [e for e in events if e.get("event") == "replace"]
    if replaces:
        out["replace_events"] = len(replaces)
        out["last_replace_moved"] = replaces[-1].get("moved")
    deaths = [
        r for r in recs
        if r.get("kind") == "fault" and r.get("action") == "replica_dead"
    ]
    if deaths:
        out["replica_dead_faults"] = len(deaths)
    return out


HOP_SEGMENTS = ("route", "queue", "wire", "remote", "respond")


def hop_summary(recs: list[dict]) -> dict | None:
    """Cross-process hop section (ISSUE 17, kind="hop"): router-side
    segments per sampled routed request. Headlines: segment medians,
    router_ms / hop_ms percentiles (hop_ms = the fleet tax on top of
    the replica's own total), the tiling check (segments sum to
    router_ms within 5% — same timestamps by construction, so the bar
    should read 1.0), per-replica sample counts, and the last clock-
    offset estimate per replica (the fleet_report skew input)."""
    hops = [
        r for r in recs
        if r.get("kind") == "hop"
        and isinstance(r.get("router_ms"), (int, float))
    ]
    if not hops:
        return None
    out: dict = {"records": len(hops)}
    proc = _process_identity(hops)
    if proc:
        out["process"] = proc

    def pct(key: str, q: float) -> float | None:
        xs = [
            float(r[key]) for r in hops
            if isinstance(r.get(key), (int, float))
        ]
        return round(_percentile(xs, q), 3) if xs else None

    for s in HOP_SEGMENTS:
        out[f"{s}_ms_p50"] = pct(f"{s}_ms", 50)
    out["router_ms_p50"] = pct("router_ms", 50)
    out["router_ms_p99"] = pct("router_ms", 99)
    out["hop_ms_p50"] = pct("hop_ms", 50)
    out["hop_ms_p99"] = pct("hop_ms", 99)
    sums_ok = sum(
        1 for r in hops
        if float(r["router_ms"]) > 0 and abs(
            sum(float(r.get(f"{s}_ms", 0.0)) for s in HOP_SEGMENTS)
            - float(r["router_ms"])
        ) <= 0.05 * float(r["router_ms"])
    )
    out["segments_sum_ok_frac"] = round(sums_ok / len(hops), 4)
    by_replica: dict[str, int] = {}
    offsets: dict[str, float] = {}
    for r in hops:
        rid = str(r.get("replica"))
        by_replica[rid] = by_replica.get(rid, 0) + 1
        if isinstance(r.get("offset_ms"), (int, float)):
            offsets[rid] = float(r["offset_ms"])
    out["by_replica"] = {k: by_replica[k] for k in sorted(by_replica)}
    if any(offsets.values()):
        out["clock_offset_ms"] = {
            k: offsets[k] for k in sorted(offsets)
        }
    return out


def recovery_summary(recs: list[dict]) -> dict | None:
    """Durable-control-plane section (ISSUE 15): journal health
    (compactions, truncated tails), cold-start recoveries (tenant /
    re-registration / catch-up counts from the last
    ``action="recovered"`` record), per-replica catch-up rows, and
    supervised restart outcomes — the recovery ledger next to the
    faults section's containment ledger."""
    faults = [r for r in recs if r.get("kind") == "fault"]
    compacts = [
        r for r in recs
        if r.get("kind") == "fleet" and r.get("event") == "journal_compact"
    ]
    recovered = [r for r in faults if r.get("action") == "recovered"]
    catchups = [r for r in faults if r.get("action") == "catchup"]
    restarts = [r for r in faults
                if r.get("action") == "replica_restarted"]
    truncated = [r for r in faults
                 if r.get("action") == "journal_truncated"]
    exhausted = [r for r in faults
                 if r.get("action") == "replica_restart_exhausted"]
    if not (recovered or catchups or restarts or truncated or compacts):
        return None
    out: dict = {}
    if recovered:
        last = recovered[-1]
        out["recoveries"] = len(recovered)
        out["last_recovery"] = {
            k: int(last[k]) for k in (
                "tenants", "reregistered", "unplaceable", "unreachable",
                "caught_up", "params_version", "journal_records",
                "snapshot_seq",
            ) if k in last
        }
    if catchups:
        out["catchup_rows"] = [
            f"{c.get('replica')}: v{int(c.get('from_version', 0))} -> "
            f"v{int(c.get('to_version', 0))}"
            for c in catchups[-5:]
        ]
    if restarts:
        ok = sum(1 for r in restarts if r.get("ok") == 1.0)
        out["replica_restarts"] = {
            "ok": ok, "failed": len(restarts) - ok,
        }
    if exhausted:
        out["restart_budget_exhausted"] = sorted(
            {str(r.get("replica")) for r in exhausted}
        )
    if truncated:
        out["journal_truncations"] = len(truncated)
        out["last_truncation"] = (
            f"{truncated[-1].get('reason')} "
            f"(-{int(truncated[-1].get('bytes_dropped', 0))} B, "
            f"{int(truncated[-1].get('records_kept', 0))} records kept)"
        )
    if compacts:
        out["journal_compactions"] = len(compacts)
        out["snapshot_seq"] = compacts[-1].get("snapshot_seq")
    return out


def elasticity_summary(recs: list[dict]) -> dict | None:
    """Elasticity section (ISSUE 16, kind="scale"): the autoscaler's
    tick timeline (replica count over pressure/idle classifications),
    completed scale decisions with the trigger signals that justified
    them, standby tail progress, and promotions — next to the fleet
    section's router ledger. ``action="scale_stuck"`` faults land in
    the faults/health sections; this is the decision ledger."""
    scale = [r for r in recs if r.get("kind") == "scale"]
    if not scale:
        return None
    ticks = [r for r in scale if "event" not in r]
    outs = [r for r in scale if r.get("event") == "scale_out"]
    drains = [r for r in scale if r.get("event") == "drain_in"]
    tails = [r for r in scale if r.get("event") == "tail"]
    promos = [r for r in scale if r.get("event") == "promotion"]
    stuck = [r for r in recs if r.get("kind") == "fault"
             and r.get("action") == "scale_stuck"]
    out: dict = {"ticks": len(ticks)}
    if ticks:
        counts = [int(r.get("replicas", 0)) for r in ticks]
        out["replicas"] = (
            f"{counts[-1]} now (min {min(counts)}, max {max(counts)} "
            f"over {len(ticks)} ticks)"
        )
        out["pressure_ticks"] = sum(
            1 for r in ticks if r.get("pressure") == 1.0
        )
        out["idle_ticks"] = sum(1 for r in ticks if r.get("idle") == 1.0)
    if outs or drains:
        out["decisions"] = [
            f"{r['event']}: {r.get('replica')} "
            + (f"warm={int(r.get('warm_compiles', 0))} " if
               r.get("event") == "scale_out" else "")
            + f"moved={int(r.get('moved', 0))} "
            f"-> {int(r.get('replicas', 0))} replicas"
            for r in (outs + drains)[-6:]
        ]
    if tails:
        out["standby_tail"] = (
            f"{len(tails)} polls with progress, "
            f"{int(tails[-1].get('applied', 0))} ops applied"
        )
    if promos:
        last = promos[-1]
        out["promotions"] = len(promos)
        out["last_promotion"] = (
            f"{last.get('promote_s')}s, "
            f"{int(last.get('tenants', 0))} tenants over "
            f"{int(last.get('replicas', 0))} replicas, "
            f"lease epoch {int(last.get('lease_epoch', 0))}, "
            f"{int(last.get('final_tail_ops', 0))} final tail ops"
        )
    if stuck:
        out["scale_stuck"] = [
            f"{r.get('direction')} {r.get('replica') or '?'}: "
            f"{r.get('reason')} (waited {r.get('waited_s')}s "
            f"of {r.get('budget_s')}s budget)"
            for r in stuck[-3:]
        ]
    return out


def health_summary(recs: list[dict]) -> dict:
    events = [r for r in recs if r.get("kind") == "health"]
    by_event: dict[str, int] = {}
    for e in events:
        by_event[str(e.get("event"))] = by_event.get(str(e.get("event")), 0) + 1
    out = {"records": len(events), "by_event": by_event}
    probes = [e for e in events if e.get("event") == "grad_probe"]
    if probes:
        cos = [
            p["grad_cosine"] for p in probes
            if isinstance(p.get("grad_cosine"), (int, float))
        ]
        if cos:
            out["grad_cosine_min"] = round(min(cos), 4)
            out["grad_cosine_last"] = round(cos[-1], 4)
    critical = [
        e for e in events
        if e.get("severity") == "critical"
    ]
    if critical:
        out["critical"] = [
            {"step": e.get("step"), "event": e.get("event"),
             "message": e.get("message")}
            for e in critical[-5:]
        ]
    return out


def mfu_summary(run_dir: Path, train: dict | None) -> dict | None:
    """Analytic MFU when the run dir carries a config.json AND the chip's
    peak is resolvable (TPU device kinds; CPU runs report n/a)."""
    if not train or not train.get("eps_mean"):
        return None
    cfg_path = run_dir / "config.json"
    if not cfg_path.exists():
        return None
    try:
        from induction_network_on_fewrel_tpu.config import ExperimentConfig
        from induction_network_on_fewrel_tpu.utils.flops import (
            peak_flops_per_chip,
            train_step_flops,
        )

        cfg = ExperimentConfig.from_json(cfg_path.read_text())
        flops = train_step_flops(cfg)
        out = {
            "flops_per_episode": flops["per_episode"],
            "achieved_flops_per_s": round(
                train["eps_mean"] * flops["per_episode"], 3
            ),
        }
        if cfg.device == "tpu":
            import jax

            kind = jax.devices()[0].device_kind
            peak = peak_flops_per_chip(kind, cfg.compute_dtype)
            if peak and jax.default_backend() == "tpu":
                out["mfu"] = round(
                    train["eps_mean"] * flops["per_episode"] / peak, 4
                )
                out["device_kind"] = kind
        return out
    except Exception as e:
        return {"error": f"mfu unavailable: {type(e).__name__}: {e}"}


def recorder_summary(run_dir: Path) -> dict | None:
    p = run_dir / "flight_recorder.json"
    if not p.exists():
        return None
    try:
        d = json.loads(p.read_text())
    except json.JSONDecodeError as e:
        return {"error": f"flight_recorder.json unreadable: {e.msg}"}
    return {
        "reason": d.get("reason"),
        "dump_count": d.get("dump_count"),
        "events": len(d.get("events", [])),
        "metrics": len(d.get("metrics", [])),
        "spans": len(d.get("spans", [])),
    }


def overhead_summary(train: dict | None, iters: int = 20000) -> dict:
    """timed_call A/B of span enter/exit cost (ISSUE 2 acceptance: < 2% of
    step time). The A/B runs the identical loop body with and without the
    span context manager; the delta per iteration is the span tax."""
    from induction_network_on_fewrel_tpu.obs.spans import SpanTracker
    from induction_network_on_fewrel_tpu.utils.profiling import timed_call

    tracker = SpanTracker(capacity=256, xplane_bridge=False)

    def with_spans():
        acc = 0
        for i in range(iters):
            with tracker.span("overhead/probe"):
                acc += i
        return acc

    def without_spans():
        acc = 0
        for i in range(iters):
            acc += i
        return acc

    # Warm both paths once (bytecode/alloc warmup), then measure.
    with_spans(), without_spans()
    _, t_with = timed_call(with_spans)
    _, t_without = timed_call(without_spans)
    per_span_s = max(0.0, (t_with - t_without) / iters)
    out = {"span_cost_us": round(per_span_s * 1e6, 3), "iters": iters}
    if train and train.get("step_time_p50_s"):
        # ~4 spans/step in the integrated loop (sample, dispatch, fetch
        # amortized, probe) — state the tax against the measured step.
        frac = 4 * per_span_s / train["step_time_p50_s"]
        out["fraction_of_p50_step"] = round(frac, 6)
        out["under_2pct"] = bool(frac < 0.02)
    return out


# --- rendering ------------------------------------------------------------

def render(report: dict) -> str:
    lines = [f"== run report: {report['run_dir']} =="]
    n, errors = report["schema"]["records"], report["schema"]["errors"]
    lines.append(f"schema: {n} records, {len(errors)} errors")
    for e in errors[:10]:
        lines.append(f"  ! {e}")
    for section in ("train", "mfu", "eval", "perf", "compile", "serve",
                    "fleet", "hops", "elasticity", "adapt", "faults",
                    "recovery",
                    "traces", "slo", "quality", "scenarios", "ckpt",
                    "input_pipeline", "comms", "roofline", "health",
                    "flight_recorder", "overhead"):
        body = report.get(section)
        if body is None:
            continue
        lines.append(f"-- {section} --")
        for k, v in body.items():
            if isinstance(v, dict) and all(
                isinstance(sv, dict) for sv in v.values()
            ) and v:
                # Table-of-dicts (e.g. serve.tenants): one row per key.
                lines.append(f"  {k}:")
                for sk in v:
                    row = " ".join(f"{a}={b}" for a, b in v[sk].items())
                    lines.append(f"    {sk}: {row}")
            elif isinstance(v, list) and v and all(
                isinstance(x, str) for x in v
            ):
                # Preformatted block (the trace waterfall): one line each.
                lines.append(f"  {k}:")
                for x in v:
                    lines.append(f"    {x}")
            else:
                lines.append(f"  {k}: {v}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render/validate the telemetry stream of one run dir"
    )
    ap.add_argument("run_dir", help="dir holding metrics.jsonl")
    ap.add_argument("--check", action="store_true",
                    help="schema validation only; exit 1 on any violation")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as one JSON object")
    ap.add_argument("--overhead", action="store_true",
                    help="measure span overhead (timed_call A/B) and state "
                         "it as a fraction of this run's p50 step time")
    args = ap.parse_args(argv)

    run_dir = Path(args.run_dir)
    metrics = run_dir / "metrics.jsonl"
    if not metrics.exists():
        print(f"no metrics.jsonl in {run_dir}", file=sys.stderr)
        return 2

    n, errors = check_schema(metrics)
    if args.check:
        for e in errors:
            print(f"schema error: {e}", file=sys.stderr)
        print(f"{'FAIL' if errors else 'OK'}: {n} records, "
              f"{len(errors)} schema errors")
        return 1 if errors else 0

    recs = load_records(metrics)
    train = train_summary(recs)
    report = {
        "run_dir": str(run_dir),
        "schema": {"records": n, "errors": errors},
        "train": train,
        "mfu": mfu_summary(run_dir, train),
        "eval": eval_summary(recs),
        "perf": perf_summary(recs),
        "compile": compile_summary(recs),
        "serve": serve_summary(recs),
        "fleet": fleet_summary(recs),
        "hops": hop_summary(recs),
        "elasticity": elasticity_summary(recs),
        "adapt": adapt_summary(recs),
        "faults": fault_summary(recs),
        "recovery": recovery_summary(recs),
        "traces": trace_summary(recs),
        "slo": slo_summary(recs),
        "quality": quality_summary(recs),
        "scenarios": scenario_summary(recs),
        "ckpt": ckpt_summary(recs),
        "input_pipeline": data_summary(recs),
        "comms": comms_summary(recs),
        "roofline": roofline_summary(recs, run_dir),
        "health": health_summary(recs),
        "flight_recorder": recorder_summary(run_dir),
    }
    if args.overhead:
        report["overhead"] = overhead_summary(train)
    if args.as_json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(render(report))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
