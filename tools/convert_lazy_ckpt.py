#!/usr/bin/env python3
"""Convert a lazy-embed checkpoint directory to a dense (shared) one.

``--embed_optimizer lazy`` checkpoints carry a different state tree than
dense runs (LazyEmbedTrainState: table moments as ``emb_m``/``emb_v``
fields, the table's optax slot masked out), so the architecture merge
refuses to restore one into a shared-mode runtime. This tool performs the
FAITHFUL conversion: materialize the table, then rebuild the dense optax
state with every Adam moment carried over — the main partition's moments
from the lazy chain's masked inner state, the word table's from
emb_m/emb_v, and all optax step counters set to the checkpoint step — so
training continued in shared mode computes the exact trajectory dense
training would have (proven at 1e-6 in tests/test_lazy_embed.py).

Caveat: lazy mode excludes weight decay from the table; a converted run
continued in shared mode with weight_decay > 0 starts applying the
coupled-L2 term to the table from the conversion point on — exact
continuation holds for wd=0 (or for the main partition always).

Usage: python tools/convert_lazy_ckpt.py SRC_DIR DST_DIR
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
        for p in path
    )


def _moment_suffix(p: str) -> str | None:
    """For an opt-state leaf path containing .../mu/... or .../nu/...,
    return 'mu:<param-suffix>' — the key both trees share."""
    for tag in ("mu", "nu"):
        marker = f"/{tag}/"
        if marker in p:
            return f"{tag}:{p.split(marker, 1)[1]}"
    return None


def convert_state(lazy_state, model, dense_cfg, emb_path):
    """LazyEmbedTrainState -> dense TrainState with moments carried over."""
    import jax
    import jax.numpy as jnp

    from induction_network_on_fewrel_tpu.train.lazy_embed import tree_get
    from induction_network_on_fewrel_tpu.train.steps import (
        TrainState,
        make_optimizer,
    )

    dense = TrainState.create(
        apply_fn=model.apply, params=lazy_state.params,
        tx=make_optimizer(dense_cfg),
    )
    # Harvest the lazy chain's moments by param-path suffix. MaskedNode
    # placeholders (the masked-out emb slot) are not arrays and are
    # skipped by the isinstance check.
    lazy_moments: dict[str, object] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        lazy_state.opt_state
    )[0]:
        key = _moment_suffix(_path_str(path))
        if key and hasattr(leaf, "shape"):
            lazy_moments[key] = leaf

    emb_suffix = "/".join(emb_path)
    step = jnp.asarray(lazy_state.step)

    def fill(path, leaf):
        p = _path_str(path)
        key = _moment_suffix(p)
        if key is not None:
            suffix = key.split(":", 1)[1]
            if suffix.endswith(emb_suffix):
                return (
                    lazy_state.emb_m if key.startswith("mu:")
                    else lazy_state.emb_v
                )
            if key in lazy_moments:
                return lazy_moments[key]
            raise KeyError(f"no lazy moment found for {p}")
        if p.endswith("count"):
            # Adam bias-correction and schedule counters both advance once
            # per update in either mode.
            return jnp.asarray(step, dtype=leaf.dtype)
        return leaf

    opt_state = jax.tree_util.tree_map_with_path(fill, dense.opt_state)
    return dense.replace(step=lazy_state.step, opt_state=opt_state)


def main(src: str, dst: str) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")  # conversion is host work
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.build import (
        batch_to_model_inputs,
    )
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from induction_network_on_fewrel_tpu.train.lazy_embed import (
        find_emb_path,
        make_materialize,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state

    cfg = CheckpointManager.load_config(src)
    if cfg.embed_optimizer != "lazy":
        print(f"{src} is not a lazy-embed checkpoint "
              f"(embed_optimizer={cfg.embed_optimizer})", file=sys.stderr)
        return 2
    # Shape-only synthetic batch to build the restore target.
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    ds = make_synthetic_fewrel(
        num_relations=max(cfg.train_n, cfg.n) * 2,
        instances_per_relation=max(cfg.k + cfg.q + 5, 20),
        vocab_size=cfg.vocab_size - 2,
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(
        ds, tok, cfg.train_n, cfg.k, cfg.q, cfg.batch_size, seed=cfg.seed
    )
    sup, qry, _ = batch_to_model_inputs(sampler.sample_batch())
    model = build_model(cfg, glove_init=vocab.vectors)

    src_mngr = CheckpointManager(src, cfg)
    target = jax.device_get(init_state(model, cfg, sup, qry))
    state, step = src_mngr.restore_best(target)
    # Carry the source's best-val metric: saving the converted state with
    # a zero metric would let ANY later val eval in the dst dir replace it
    # (best_fn keeps the max), silently discarding the better weights.
    metrics = src_mngr.mngr.metrics(step) or {}
    best_val = float(metrics.get("val_accuracy", 0.0))
    src_mngr.close()
    state = make_materialize(cfg)(state)

    dense_cfg = cfg.replace(embed_optimizer="shared")
    dense = convert_state(state, model, dense_cfg, find_emb_path(state.params))

    dst_mngr = CheckpointManager(dst, dense_cfg)
    dst_mngr.save(step, dense, val_accuracy=best_val)
    dst_mngr.close()
    print(f"converted step {step} (best_val {best_val:.4f}): "
          f"{src} (lazy) -> {dst} (shared)")
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 3:
        print(__doc__, file=sys.stderr)
        sys.exit(2)
    sys.exit(main(sys.argv[1], sys.argv[2]))
