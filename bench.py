#!/usr/bin/env python3
"""Throughput benchmark: training episodes/sec/chip on the flagship config.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Config: FewRel-style 5-way 5-shot, BiLSTM+self-attention induction network,
L=40, bf16 compute — the reference's headline setup (BASELINE.json config #2)
— full END-TO-END train steps through the production ``--token_cache`` path:
the tokenized dataset lives device-resident, the host episodic sampler
streams only index batches, and every step runs the complete fwd+bwd+update
(the encoder trains; this is a transport optimization, not reduced work).
Measured 2026-07-30 vs the live-token path, interleaved A/B at spc=64:
3374 vs 863 eps/s/chip median (~3.9x) — the tunneled host->device link, not
the device, was the flagship bottleneck.

Timing is chunked, wall-clock-bounded, and — critically — HARD-SYNCED: every
chunk ends with a device_get of a loss scalar. On this machine's tunneled
backend ``jax.block_until_ready`` does NOT actually wait for execution (a
queue of 500 "completed" steps drained for 6+ more seconds on the first real
value fetch, measured 2026-07-30); only a value fetch forces completion.
Block-based timings measured dispatch throughput, not training throughput —
every pre-2026-07-30 number in BASELINE.md is such an illusion and is
superseded by the hard-synced numbers.

``vs_baseline``: ratio against the first HONEST (hard-synced) bench.py run:
1264 eps/s/chip, pallas BiLSTM, steps_per_call=64, 2026-07-30 (best scratch
observation that day: 1840 — honest-mode tunnel variance is ±30%).
The reference repo itself has no published numbers (BASELINE.json
``published`` is empty), so the self-established number is the bar all later
rounds must beat.
"""

from __future__ import annotations

import json
import sys
import time

import os

# First HONEST (hard-synced) measured number for this config — the
# self-established baseline later rounds improve against (BASELINE.md).
# On non-TPU backends vs_baseline is reported as 1.0 (not comparable).
BASELINE_EPS_TPU = 1264.0

BATCH = 8            # episodes per step
# Optimizer steps fused per dispatch (lax.scan). Hard-synced sweep on the
# tunneled TPU, token-cache path (2026-07-30): spc 64 -> 3066, 128 -> 3531,
# 256 -> 4166, 512 -> 4553, 1024 -> 4684 eps/s TRUE. 512 balances the
# asymptote against chunk granularity (device busy ~1.3 ms/step puts the
# ceiling near 6.3k at B=8).
STEPS_PER_CALL = int(os.environ.get("BENCH_SPC", "512"))
WARMUP_STEPS = 5
CHUNK_STEPS = 2 * STEPS_PER_CALL
MAX_STEPS = 8192
MAX_SECONDS = 60.0


def _probe_tpu(timeout: float = 90.0) -> bool:
    """Check (in a subprocess) that TPU backend init completes.

    The axon tunnel can die mid-session, in which case backend init blocks
    forever; probing in a killable child keeps the bench from hanging —
    it falls back to the CPU backend and says so in the metric name.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    import jax

    if not _probe_tpu():
        print("bench: TPU backend unreachable; falling back to CPU", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.train.feature_cache import (
        FeatureEpisodeSampler,
    )
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_train_step,
        tokenize_dataset,
    )

    backend = jax.default_backend()
    n_chips = jax.local_device_count()
    print(f"bench: backend={backend} chips={n_chips}", file=sys.stderr)

    # The deep-fusion default is sized for the TPU; on the CPU fallback a
    # 512-step fused call (and 1024-step chunks between MAX_SECONDS checks)
    # would grind for many minutes before the first timing line.
    global STEPS_PER_CALL, CHUNK_STEPS, MAX_STEPS
    if backend != "tpu":
        STEPS_PER_CALL = min(STEPS_PER_CALL, 16)
        CHUNK_STEPS = 2 * STEPS_PER_CALL
        MAX_STEPS = min(MAX_STEPS, 256)

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=BATCH, max_length=40,
        vocab_size=2002, compute_dtype="bfloat16",
        steps_per_call=STEPS_PER_CALL, token_cache=True,
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=20, instances_per_relation=cfg.k + cfg.q + 5,
        vocab_size=cfg.vocab_size - 2,
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    # Device-resident token cache (train/token_cache.py, the production
    # --token_cache path): the tokenized dataset is uploaded ONCE; per step
    # only [B,N,K]+[B,TQ] int32 episode indices cross the host->device
    # tunnel and the token gather runs inside the jitted step. Full
    # training semantics — the encoder trains and backprops every step.
    table_np, sizes = tokenize_dataset(ds, tok)
    table = jax.device_put(table_np)
    sampler = FeatureEpisodeSampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0
    )
    model = build_model(cfg, glove_init=vocab.vectors)

    import numpy as np

    b0 = sampler.sample_batch()
    sup = {k: v[b0.support_idx] for k, v in table_np.items()}
    qry = {k: v[b0.query_idx] for k, v in table_np.items()}
    state = init_state(model, cfg, sup, qry)
    multi_step = make_token_cached_multi_train_step(model, cfg)
    S = STEPS_PER_CALL

    def fused_call(state):
        batches = [sampler.sample_batch() for _ in range(S)]
        si = np.stack([b.support_idx for b in batches])
        qi = np.stack([b.query_idx for b in batches])
        lab = np.stack([b.label for b in batches])
        return multi_step(state, table, si, qi, lab)

    t0 = time.monotonic()
    for _ in range(max(WARMUP_STEPS // S, 2)):
        state, metrics = fused_call(state)
    # HARD SYNC: a value fetch, not block_until_ready — on this tunneled
    # backend block_until_ready returns before execution finishes (see
    # module docstring), so only fetching a scalar forces the queue to
    # actually drain. Every chunk below ends the same way.
    _ = float(jax.device_get(metrics["loss"])[-1])
    print(f"bench: warmup(+compile) {time.monotonic() - t0:.1f}s", file=sys.stderr)

    best_rate = 0.0
    total_steps = 0
    calls_per_chunk = max(CHUNK_STEPS // S, 1)
    bench_start = time.monotonic()
    while total_steps < MAX_STEPS and time.monotonic() - bench_start < MAX_SECONDS:
        t0 = time.monotonic()
        for _ in range(calls_per_chunk):
            state, metrics = fused_call(state)
        _ = float(jax.device_get(metrics["loss"])[-1])  # hard sync
        dt = time.monotonic() - t0
        chunk_steps = calls_per_chunk * S
        total_steps += chunk_steps
        rate = chunk_steps * BATCH / dt / max(n_chips, 1)
        best_rate = max(best_rate, rate)
        print(
            f"bench: chunk {total_steps // chunk_steps}: {dt:.3f}s "
            f"-> {rate:.0f} eps/s/chip", file=sys.stderr,
        )

    # Comparable to the recorded TPU baseline only on TPU.
    comparable = backend == "tpu"
    vs = best_rate / BASELINE_EPS_TPU if comparable else 1.0
    print(json.dumps({
        "metric": (
            f"train_episodes_per_sec_per_chip"
            f"[5w5s,bilstm,L40,bf16,{backend},e2e,tokencache,spc{S},hardsync]"
        ),
        "value": round(best_rate, 2),
        "unit": "episodes/s/chip",
        "vs_baseline": round(vs, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
