#!/usr/bin/env python3
"""Throughput benchmark: training episodes/sec/chip on the flagship config.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Config: FewRel-style 5-way 5-shot, BiLSTM+self-attention induction network,
L=40, bf16 compute — the reference's headline setup (BASELINE.json config #2)
— full jitted train steps (fwd+bwd+update, donated state) on synthetic
schema-faithful episodes so the number does not depend on data files.

``vs_baseline``: ratio against the first recorded TPU v5e measurement
(BASELINE.md "measured" table). Until that row exists the ratio is 1.0 by
construction (the reference repo has no published numbers — BASELINE.json
``published`` is empty).
"""

from __future__ import annotations

import json
import sys
import time

# First measured TPU v5e litepod-1 number (episodes/sec/chip) — the
# self-established baseline all later rounds improve against (BASELINE.md).
BASELINE_EPS: float | None = None

BATCH = 8          # episodes per step
WARMUP_STEPS = 3
TIMED_STEPS = 30


def main() -> int:
    import jax

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.models.build import batch_to_model_inputs
    from induction_network_on_fewrel_tpu.sampling import EpisodeSampler
    from induction_network_on_fewrel_tpu.train.steps import init_state, make_train_step

    backend = jax.default_backend()
    n_chips = jax.local_device_count()
    print(f"bench: backend={backend} chips={n_chips}", file=sys.stderr)

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=BATCH, max_length=40,
        vocab_size=2002, compute_dtype="bfloat16",
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    ds = make_synthetic_fewrel(
        num_relations=20, instances_per_relation=cfg.k + cfg.q + 5,
        vocab_size=cfg.vocab_size - 2,
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    sampler = EpisodeSampler(ds, tok, cfg.n, cfg.k, cfg.q, cfg.batch_size, seed=0)
    model = build_model(cfg, glove_init=vocab.vectors)

    batches = [batch_to_model_inputs(sampler.sample_batch()) for _ in range(8)]
    sup, qry, _ = batches[0]
    state = init_state(model, cfg, sup, qry)
    step = make_train_step(model, cfg)

    t0 = time.monotonic()
    for i in range(WARMUP_STEPS):
        state, metrics = step(state, *batches[i % len(batches)])
    jax.block_until_ready(metrics)
    print(f"bench: warmup(+compile) {time.monotonic() - t0:.1f}s", file=sys.stderr)

    t0 = time.monotonic()
    for i in range(TIMED_STEPS):
        state, metrics = step(state, *batches[i % len(batches)])
    jax.block_until_ready(metrics)
    dt = time.monotonic() - t0

    eps_per_chip = TIMED_STEPS * BATCH / dt / max(n_chips, 1)
    vs = eps_per_chip / BASELINE_EPS if BASELINE_EPS else 1.0
    print(json.dumps({
        "metric": f"train_episodes_per_sec_per_chip[5w5s,bilstm,L40,bf16,{backend}]",
        "value": round(eps_per_chip, 2),
        "unit": "episodes/s/chip",
        "vs_baseline": round(vs, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
