#!/usr/bin/env python3
"""Throughput benchmark: training episodes/sec/chip + MFU, reference-shaped.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

Headline config (BASELINE.json config #2's cost structure): FewRel-style
5-way 5-shot, BiLSTM+self-attention induction network, L=40, bf16 compute,
**vocab_size=400002** — the full GloVe 400k+UNK+BLANK table (synthetic
values, real shapes) with the reference-parity DENSE Adam update on the
table every step (embed_optimizer=shared). Episode batch B=64: the dense
table update is a fixed per-step cost, so batching episodes amortizes it
(measured 2026-07-30: B=8 -> 1457, B=32 -> 3250, B=64 -> 3542 eps/s/chip;
B=128 adds ~5% more — 64 balances latency vs the asymptote).

Transport: the production ``--token_cache`` path — the tokenized dataset
lives device-resident, and the C++ index sampler
(native/episode_sampler.cpp ``inf_sampler_sample_indices``) streams stacked
[S,B,·] episode-index batches at ~1-2M eps/s host-side (the Python index
sampler's ~6k eps/s was the flagship bottleneck, measured 2026-07-30: the
legacy small-vocab config jumped 4850 -> 5835 eps/s from this alone).
Every step runs the complete fwd+bwd+update — the encoder trains.

MFU: analytic matmul FLOPs/step (utils/flops.py — PaLM-convention: 3x
forward matmuls, elementwise/optimizer excluded) divided by wall time and
the chip's peak (v5e bf16: 197 TFLOP/s).

Timing is chunked, wall-clock-bounded, and — critically — HARD-SYNCED:
every chunk ends with a device_get of a loss scalar. On this machine's
tunneled backend ``jax.block_until_ready`` does NOT actually wait for
execution (a queue of 500 "completed" steps drained for 6+ more seconds on
the first real value fetch, measured 2026-07-30); only a value fetch forces
completion. Block-based timings measured dispatch throughput, not training
throughput — every pre-2026-07-30 number in BASELINE.md is such an illusion
and is superseded by the hard-synced numbers.

``vs_baseline``: ratio against the first HONEST (hard-synced) bench.py run:
1264 eps/s/chip (pallas BiLSTM, spc=64, vocab=2002, 2026-07-30; honest-mode
tunnel variance is ±30%). The reference repo has no published numbers
(BASELINE.json ``published`` is empty), so that self-established number is
the bar — note today's headline config does strictly MORE work per episode
(200x the vocab, dense Adam on the full table) than the config the bar was
set on. Env overrides: BENCH_VOCAB, BENCH_B, BENCH_SPC, BENCH_EMBED.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Per-config self-established baselines (BASELINE.md): the best recorded
# bench.py run of each (vocab, B, spc, embed) configuration, so vs_baseline
# is a like-for-like ratio instead of dividing by a bar measured on a
# different config (round-2 VERDICT weak item 2). Keyed by config; the
# legacy first-honest-run bar is the fallback for unrecorded configs.
BASELINES_EPS_TPU = {
    (400002, 64, 256, "shared"): 3538.0,  # BENCH_r02 (round-2 headline)
    # Round-4 level (BASELINE.md round 4): projection-fused Pallas kernels
    # (driver-validated at 11,432 in BENCH_r03) + time-major gathers +
    # hoisted lazy scan + position offsets -> 16,217 at spc=256; the
    # spc re-sweep then settled the default at 512 -> 17,083. Bars at the
    # lower edge of the observed bands so tunnel weather doesn't read as
    # a regression. (History: r3 in-session bar 9,135; r4 mid-round
    # 13,400; pre-optimization 4,497.)
    (400002, 64, 512, "lazy"): 16200.0,
    (400002, 64, 256, "lazy"): 15300.0,
    # Dense-parity twin at the new spc default (same session as the lazy
    # 512 bar: cached shared was 6,466 at spc=256 interleaved; bar set
    # below it because shared's per-step dense table update amortizes
    # LESS with spc, not more — without this entry a BENCH_EMBED=shared
    # run would silently fall back to the 1,264 legacy bar).
    (400002, 64, 512, "shared"): 6000.0,
    (2002, 8, 512, "shared"): 5185.0,     # round-1 best (legacy config)
}
BASELINE_EPS_FALLBACK = 1264.0  # first honest hard-synced run ever (r1)

# Driver-recorded END-OF-ROUND numbers (BENCH_r{N}.json), per config.
# ``vs_prev_round`` divides by these, so the artifact itself carries the
# cross-round trajectory: vs_baseline is re-barred within a round (honest
# about tunnel weather, silent about progress — round-4 VERDICT weak item
# 5), while this ratio is pinned to what the driver measured LAST round.
PREV_ROUND_EPS_TPU = {
    (400002, 64, 512, "lazy"): 16471.25,   # BENCH_r04
    (400002, 64, 256, "lazy"): 11432.68,   # BENCH_r03
    (400002, 64, 256, "shared"): 3538.24,  # BENCH_r02
}

VOCAB = int(os.environ.get("BENCH_VOCAB", "400002"))
BATCH = int(os.environ.get("BENCH_B", "64"))
# Optimizer steps fused per dispatch (lax.scan). Round-4 re-sweep at the
# 16k-eps/s balance: 128 -> 15,193, 256 -> 16,221, 512 -> 17,083 (the
# per-call fixed terms — lazy prologue/epilogue, dispatch RPC, hard-sync
# fetch — keep amortizing); 512 keeps chunks under ~4 s.
STEPS_PER_CALL = int(os.environ.get("BENCH_SPC", "512"))
# "lazy" = the exact-parity sparse table Adam (train/lazy_embed.py,
# equivalence proven at 1e-6 in tests/test_lazy_embed.py) — the production
# recommendation and round-3 headline: 4,497 vs dense-shared's 3,532
# eps/s/chip, measured interleaved. BENCH_EMBED=shared reproduces the
# reference-parity dense path.
EMBED_OPT = os.environ.get("BENCH_EMBED", "lazy")
WARMUP_CALLS = 2
MAX_SECONDS = 60.0


def _probe_tpu(timeout: float = 90.0) -> bool:
    """Check (in a subprocess) that TPU backend init completes.

    The axon tunnel can die mid-session, in which case backend init blocks
    forever; probing in a killable child keeps the bench from hanging —
    it falls back to the CPU backend and says so in the metric name.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    import jax

    if not _probe_tpu():
        print("bench: TPU backend unreachable; falling back to CPU", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import (
        GloveTokenizer,
        make_synthetic_fewrel,
        make_synthetic_glove,
    )
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.native.sampler import make_index_sampler
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_train_step,
        tokenize_dataset,
    )
    from induction_network_on_fewrel_tpu.utils.flops import (
        bilstm_induction_train_flops,
        peak_flops_per_chip,
    )

    backend = jax.default_backend()
    n_chips = jax.local_device_count()
    print(f"bench: backend={backend} chips={n_chips}", file=sys.stderr)

    global VOCAB, BATCH, STEPS_PER_CALL
    if backend != "tpu":
        # CPU fallback: the full-table config would grind for many minutes
        # before the first timing line; shrink to stay responsive.
        VOCAB = min(VOCAB, 2002)
        BATCH = min(BATCH, 8)
        STEPS_PER_CALL = min(STEPS_PER_CALL, 16)

    cfg = ExperimentConfig(
        encoder="bilstm", n=5, k=5, q=5, batch_size=BATCH, max_length=40,
        vocab_size=VOCAB, compute_dtype="bfloat16",
        steps_per_call=STEPS_PER_CALL, token_cache=True,
        embed_optimizer=EMBED_OPT,
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2)
    # Dataset size is independent of the vocab table: sentences draw from
    # the first <=2000 words; the table's 400k rows still cost the full
    # dense Adam update (the reference configuration's dominant term).
    ds = make_synthetic_fewrel(
        num_relations=20, instances_per_relation=cfg.k + cfg.q + 5,
        vocab_size=min(cfg.vocab_size - 2, 2000),
    )
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    table_np, sizes = tokenize_dataset(ds, tok)
    if cfg.embed_optimizer == "lazy":
        # Precomputed corpus remap: the cached lazy body trains the
        # corpus-restricted sub-table directly (train/lazy_embed.py).
        from induction_network_on_fewrel_tpu.train.lazy_embed import (
            augment_token_table,
        )

        table_np, uids = augment_token_table(table_np)
        table_np = {**table_np, "uids": uids}
    table = jax.device_put(table_np)
    sampler = make_index_sampler(
        sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size, seed=0
    )
    model = build_model(cfg, glove_init=vocab.vectors)

    try:
        return _run_bench(jax, cfg, model, sampler, table, table_np, backend, n_chips)
    finally:
        sampler.close()  # native handle: deterministic release, not __del__


def _run_bench(jax, cfg, model, sampler, table, table_np, backend, n_chips) -> int:
    from induction_network_on_fewrel_tpu.train.steps import init_state
    from induction_network_on_fewrel_tpu.train.token_cache import (
        make_token_cached_multi_train_step,
    )
    from induction_network_on_fewrel_tpu.utils.flops import (
        bilstm_induction_train_flops,
        peak_flops_per_chip,
    )

    b0s, b0q, _ = sampler.sample_fused(1)
    # "uids" is table-level metadata (lazy mode), not a per-row column.
    sup = {k: v[b0s[0]] for k, v in table_np.items() if k != "uids"}
    qry = {k: v[b0q[0]] for k, v in table_np.items() if k != "uids"}
    state = init_state(model, cfg, sup, qry)
    multi_step = make_token_cached_multi_train_step(model, cfg)
    S = STEPS_PER_CALL

    def fused_call(state):
        si, qi, lab = sampler.sample_fused(S)
        return multi_step(state, table, si, qi, lab)

    t0 = time.monotonic()
    for _ in range(WARMUP_CALLS):
        state, metrics = fused_call(state)
    # HARD SYNC: a value fetch, not block_until_ready — on this tunneled
    # backend block_until_ready returns before execution finishes (see
    # module docstring), so only fetching a scalar forces the queue to
    # actually drain. Every chunk below ends the same way.
    _ = float(jax.device_get(metrics["loss"])[-1])
    print(f"bench: warmup(+compile) {time.monotonic() - t0:.1f}s", file=sys.stderr)

    best_rate = 0.0
    total_steps = 0
    chunk = 0
    bench_start = time.monotonic()
    while time.monotonic() - bench_start < MAX_SECONDS:
        t0 = time.monotonic()
        # Two calls per chunk: call 2's host-side sampling (a few ms with
        # the C++ sampler) overlaps call 1's device execution.
        state, metrics = fused_call(state)
        state, metrics = fused_call(state)
        _ = float(jax.device_get(metrics["loss"])[-1])  # hard sync
        dt = time.monotonic() - t0
        chunk_steps = 2 * S
        total_steps += chunk_steps
        chunk += 1
        rate = chunk_steps * BATCH / dt / max(n_chips, 1)
        best_rate = max(best_rate, rate)
        print(
            f"bench: chunk {chunk}: {dt:.3f}s -> {rate:.0f} eps/s/chip",
            file=sys.stderr,
        )

    # Boundary-soak leg (ISSUE 3 satellite): a short windowed-vs-all-in
    # measurement with a ring checkpoint save after every chunk — the
    # warm-soak all-in/windowed ratio in miniature, tracked per round so
    # the delta-ring byte diet shows up in BENCH_* artifacts, not only in
    # soak prose. Ring saves go through train/checkpoint.py save_latest:
    # base + touched-row deltas for the lazy config, full otherwise.
    # Runs BEFORE the device-busy trace: each fused call donates the state
    # buffers, so the state must thread through, and the trace leg is the
    # one consumer that doesn't return it.
    allin_over_windowed, ring_bytes, state = _boundary_soak(
        jax, cfg, fused_call, state, best_rate, n_chips
    )

    # Input-pipeline leg (ISSUE 4): the datapipe producer feed at prefetch
    # depths {0, 2, 4} — feed_stall_frac (fraction of wall the trainer
    # waited on the feed; at depth 0 that is the fully-serial baseline's
    # inline sampling) and eps_per_sec per depth, so the overlap win sits
    # in the BENCH trajectory, not only in soak prose.
    datapipe_leg = None
    try:
        # CPU fallback keeps the leg responsive: one timed call per depth
        # (the fused call itself is tens of seconds there, and the stall
        # measurement is a within-call integral, not a between-call
        # variance estimate); TPU gets the full 6-call window.
        datapipe_leg, state = _datapipe_leg(
            jax, cfg, multi_step, sampler, table, state, n_chips,
            calls=6 if backend == "tpu" else 1,
        )
    except Exception as e:  # the leg must never sink the bench
        print(f"bench: datapipe leg failed: {e!r}", file=sys.stderr)

    # Serving leg (ISSUE 7): continuous-vs-microbatch scheduler A/B on a
    # small in-process engine — closed-loop throughput + p99 at fixed
    # concurrency, per scheduler, so the fleet-serving win rides the BENCH
    # trajectory (CPU-honest: the CPU number compares schedulers, not
    # chips; SERVE_r*.json from tools/loadgen.py is the full artifact).
    serving_leg = None
    try:
        serving_leg = _serving_leg(
            jax, seconds=3.0 if backend == "tpu" else 1.5
        )
    except Exception as e:  # the leg must never sink the bench
        print(f"bench: serving leg failed: {e!r}", file=sys.stderr)

    # Scenarios leg (ISSUE 10): the miniature DA+NOTA quality run
    # (tools/scenarios.py run_tier1 — the same leg tier-1 gates against
    # SCENARIOS_r*.json), so every BENCH artifact carries model-quality
    # numbers next to its throughput numbers. CPU-honest: the miniature
    # world trains in seconds on either backend.
    scenarios_leg = None
    try:
        scenarios_leg = _scenarios_leg()
    except Exception as e:  # the leg must never sink the bench
        print(f"bench: scenarios leg failed: {e!r}", file=sys.stderr)

    # Device-busy fraction (VERDICT round-2 weak item 1): one traced chunk,
    # parsed from the XPlane via jax.profiler.ProfileData — puts "how much
    # of the wall is device work vs tunnel RPC" in the artifact itself
    # instead of BASELINE.md prose.
    device_busy = None
    try:
        device_busy = _device_busy_fraction(jax, fused_call, state)
    except Exception as e:  # profiling must never sink the bench
        print(f"bench: device-busy capture failed: {e!r}", file=sys.stderr)

    flops = bilstm_induction_train_flops(cfg)
    peak = peak_flops_per_chip(
        jax.devices()[0].device_kind, cfg.compute_dtype
    )
    mfu = (
        round(best_rate * flops["per_episode"] / peak, 4)
        if peak is not None else None
    )

    # Comparable to the recorded TPU baselines only on TPU; ratio is
    # against THIS config's own recorded bar when one exists.
    comparable = backend == "tpu"
    bar = BASELINES_EPS_TPU.get(
        (VOCAB, BATCH, STEPS_PER_CALL, EMBED_OPT), BASELINE_EPS_FALLBACK
    )
    vs = best_rate / bar if comparable else 1.0
    prev = PREV_ROUND_EPS_TPU.get((VOCAB, BATCH, STEPS_PER_CALL, EMBED_OPT))
    vs_prev = (
        round(best_rate / prev, 3) if (comparable and prev) else None
    )
    # Analytic HBM bytes/step at THIS config (shared formulas with the
    # roofline ledger, utils/roofline.py) — the byte-diet number the
    # round-6 tentpole targets, stamped into every bench artifact. Round 7
    # adds the collective terms at the flagship dp=8 mesh (the comms
    # ledger's shape — the bench itself may run single-chip, so the comms
    # row is the projection for the sharded deployment, same arithmetic
    # tools/comms_ledger.py asserts the compiled HLO against).
    from induction_network_on_fewrel_tpu.utils.roofline import (
        comms_payload_bytes,
        comms_wire_bytes,
        lstm_residual_bytes,
        step_bytes,
    )

    comms_cfg = cfg.replace(dp=8)
    # Real corpus bound for the demb [U, D] term when the lazy table is in
    # hand (round-7 review finding: the synthetic default understates real
    # corpora several-fold).
    comms_u = (
        int(table_np["uids"].shape[0]) if "uids" in table_np else None
    )

    summary = {
        "metric": (
            f"train_episodes_per_sec_per_chip"
            f"[5w5s,bilstm,L40,bf16,{backend},e2e,tokencache,"
            f"vocab{VOCAB},B{BATCH},spc{S},embed_{EMBED_OPT},hardsync]"
        ),
        "value": round(best_rate, 2),
        "unit": "episodes/s/chip",
        "vs_baseline": round(vs, 3),
        "vs_prev_round": vs_prev,
        "mfu": mfu,
        "device_busy": device_busy,
        "flops_per_episode": flops["per_episode"],
        # step_bytes keeps its round-6/7 meaning (full-cs kernel, W=0) so
        # the stamp stays comparable across rounds; step_bytes_windowed is
        # the round-8 production design at the config's resolved residual
        # knobs, and lstm_residual_bytes is the diet headline — the bytes
        # the forward writes solely for the backward (ROOFLINE_r08).
        "step_bytes": step_bytes(cfg, corpus_rows=comms_u, lstm_cs_window=0),
        "step_bytes_no_remat": step_bytes(
            cfg, remat_attn=False, corpus_rows=comms_u, lstm_cs_window=0
        ),
        "step_bytes_windowed": step_bytes(cfg, corpus_rows=comms_u),
        "lstm_residual_bytes": lstm_residual_bytes(cfg),
        # Lazy legs only: the comms arithmetic models the compact demb of
        # the lazy/token-cache path — a shared-embed leg's sharded compile
        # schedules full-table-shaped demb collectives it doesn't carry
        # (null = "unmodeled here, see the ledger", never a wrong number).
        "comms_bytes_per_step": (
            int(comms_payload_bytes(comms_cfg, corpus_rows=comms_u))
            if cfg.embed_optimizer == "lazy" else None
        ),
        "comms_wire_bytes_per_step": (
            int(comms_wire_bytes(comms_cfg, corpus_rows=comms_u))
            if cfg.embed_optimizer == "lazy" else None
        ),
        # Round 10: measured whole-step overlap headline + per-bucket AR
        # bytes, republished from the newest committed comms-ledger
        # artifact (the bench itself may run single-chip; the ledger's
        # dp=8 compile is where overlap is actually measured). Same
        # lazy-leg gating as the projections above.
        **(_comms_overlap_stamp()
           if cfg.embed_optimizer == "lazy"
           else {"comms_overlap_frac": None,
                 "comms_unoverlapped_frac": None,
                 "comms_bucket_bytes": None}),
        "allin_over_windowed": allin_over_windowed,
        "ring_save_bytes": ring_bytes,
        "datapipe": datapipe_leg,
        "serving": serving_leg,
        "scenarios": scenarios_leg,
        # Per-geometry roofline rows (ISSUE 19): the paper's (N, K) eval
        # grid priced analytically at THIS config — episode FLOPs and
        # HBM step bytes scale with the episode geometry, and the grid
        # rows put 5w1s/10w1s/10w5s next to the flagship's numbers in
        # every bench artifact (same shared formulas as the ledgers).
        "geometry": _geometry_rows(cfg, comms_u),
    }
    print(json.dumps(summary))
    _append_trend_input(summary, backend)
    return 0


def _append_trend_input(summary: dict, backend: str) -> None:
    """Append this run's summary to the bench-trajectory input (ISSUE 11):
    tools/bench_trend.py folds every row of TREND_INPUT.jsonl into the
    TREND.json timeseries next to the committed BENCH_r*.json artifacts,
    so the trajectory is populated by every bench run from now on — not
    only by driver-committed rounds. Append-only JSON lines; the metric
    string carries the backend, so CPU-fallback rows never share a band
    with TPU rounds. Best-effort: a read-only checkout must not sink the
    bench. BENCH_TREND_FILE overrides the destination ('' disables)."""
    dest = os.environ.get("BENCH_TREND_FILE")
    if dest == "":
        return
    path = dest or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "TREND_INPUT.jsonl")
    row = {"unix_s": round(time.time(), 1), "backend": backend, **summary}
    try:
        with open(path, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(f"bench: appended run summary to {path}", file=sys.stderr)
    except OSError as e:
        print(f"bench: trend-input append failed: {e!r}", file=sys.stderr)


def _comms_overlap_stamp() -> dict:
    """Measured comms-overlap headline for the bench stamp (ISSUE 20).

    The overlap fraction is a property of the sharded dp=8 compile, which
    tools/comms_ledger.py measures (round 10+: every leg carries an
    ``overlap`` section — per-collective dataflow windows priced at the
    v5e HBM:ICI ratio). The bench itself may run single-chip, so this
    does NOT re-measure: it republishes the flagship leg's committed
    measurement — overlap_frac / unoverlapped_frac plus the per-bucket
    all-reduce payload bytes grouped from the attributed rows — so every
    bench artifact carries the comms headline next to the wire-byte
    projection and TREND.json folds both. Nulls when no round-10+
    artifact is present (old checkouts), never a wrong number."""
    import glob
    import re as _re

    here = os.path.dirname(os.path.abspath(__file__))
    flag = None
    for path in sorted(glob.glob(os.path.join(here, "COMMS_r*.json"))):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        leg = (data.get("dp8_tokencache_lazy_flagship") or {}) \
            if isinstance(data, dict) else {}
        if isinstance(leg.get("overlap"), dict):
            flag = leg  # newest round wins (sorted r05 < r10 < ...)
    if flag is None:
        return {"comms_overlap_frac": None,
                "comms_unoverlapped_frac": None,
                "comms_bucket_bytes": None}
    ov = flag["overlap"]
    buckets: dict[str, int] = {}
    for row in ov.get("collectives") or []:
        m = _re.search(r"grad/bucket_(\d+)", str(row.get("source") or ""))
        if m:
            key = f"bucket_{m.group(1)}"
            buckets[key] = buckets.get(key, 0) + int(row.get("bytes") or 0)
    return {
        "comms_overlap_frac": ov.get("overlap_frac"),
        "comms_unoverlapped_frac": ov.get("unoverlapped_frac"),
        "comms_bucket_bytes": dict(sorted(buckets.items())) or None,
    }


def _geometry_rows(cfg, corpus_rows=None) -> dict:
    """{<N>w<K>s: {flops_per_episode, step_bytes, lstm_residual_bytes}}
    over the paper eval grid — analytic, from the same utils/flops +
    utils/roofline formulas the headline row uses, at the config's
    resolved knobs with only the episode geometry replaced."""
    import dataclasses

    from induction_network_on_fewrel_tpu.serving.geometry import GRID, grid_key
    from induction_network_on_fewrel_tpu.utils.flops import (
        bilstm_induction_train_flops,
    )
    from induction_network_on_fewrel_tpu.utils.roofline import (
        lstm_residual_bytes,
        step_bytes,
    )

    rows = {}
    for n, k in GRID:
        gcfg = dataclasses.replace(cfg, train_n=n, n=n, k=k)
        rows[grid_key(n, k)] = {
            "flops_per_episode":
                bilstm_induction_train_flops(gcfg)["per_episode"],
            "step_bytes": step_bytes(
                gcfg, corpus_rows=corpus_rows, lstm_cs_window=0
            ),
            "lstm_residual_bytes": lstm_residual_bytes(gcfg),
        }
    return rows


def _scenarios_leg():
    """The tier-1 miniature quality numbers (tools/scenarios.py), flat:
    in-domain / cross-domain / DA-mixture accuracy + NOTA best-F1 — the
    same headline block SCENARIOS_r*.json records and tier-1 bands."""
    from tools.scenarios import run_tier1, tier1_headline

    res = run_tier1(seed=1)
    head = tier1_headline(res)
    out = {
        k: head[k] for k in (
            "in_domain_accuracy", "cross_domain_accuracy",
            "da_mixture_accuracy", "nota_best_f1",
        )
    }
    # Per-(N, K) grid accuracies with CIs (ISSUE 19) — the miniature
    # world's grid, banded in TREND via the GEOM artifact's copy.
    out["grid"] = {
        key: {"accuracy": leg["accuracy"], "acc_ci95": leg["acc_ci95"]}
        for key, leg in res.get("grid", {}).items()
    }
    out["wall_s"] = res["wall_s"]
    print(
        f"bench: scenarios: in-domain {out['in_domain_accuracy']}, "
        f"cross-domain {out['cross_domain_accuracy']}, da "
        f"{out['da_mixture_accuracy']}, nota f1 {out['nota_best_f1']} "
        f"({out['wall_s']}s)",
        file=sys.stderr,
    )
    return out


def _serving_leg(jax, seconds: float = 1.5, tenants: int = 2,
                 concurrency: int = 4):
    """{scheduler: {qps, p50_ms, p99_ms, occupancy, steady_recompiles,
    trace}} — the same closed loop driven through the continuous and
    micro-batch schedulers on a small in-process engine (2 tenants,
    fresh-init weights; tiny cnn encoder so the leg's 2x4 bucket compiles
    stay seconds on CPU). The comparison is scheduler-relative:
    everything else — model, tenants, traffic — is identical across
    arms. The load loop and percentile convention are tools/loadgen.py's
    own (one home — a fix to either applies to both harnesses). ``trace``
    carries the sampled segment-breakdown medians + exemplar trace_ids
    (ISSUE 9), so a scheduler A/B in the BENCH trajectory attributes
    WHICH stage moved (queue vs pack vs execute), not just e2e p99."""
    import argparse

    import numpy as np

    from induction_network_on_fewrel_tpu.config import ExperimentConfig
    from induction_network_on_fewrel_tpu.data import make_synthetic_glove
    from induction_network_on_fewrel_tpu.data.tokenizer import GloveTokenizer
    from induction_network_on_fewrel_tpu.models import build_model
    from induction_network_on_fewrel_tpu.serving.buckets import zero_batch
    from induction_network_on_fewrel_tpu.serving.engine import InferenceEngine
    from tools.loadgen import _flat, _pools, pct, register_tenants, run_closed

    cfg = ExperimentConfig(
        model="induction", encoder="cnn", hidden_size=32,
        vocab_size=2002, max_length=32, n=5, train_n=5, k=5, q=5,
        device="cpu" if jax.default_backend() != "tpu" else "tpu",
    )
    vocab = make_synthetic_glove(vocab_size=cfg.vocab_size - 2,
                                 word_dim=cfg.word_dim)
    tok = GloveTokenizer(vocab, max_length=cfg.max_length)
    model = build_model(cfg, glove_init=vocab.vectors)
    params = model.init(
        jax.random.key(0),
        zero_batch(cfg.max_length, (1, cfg.n, cfg.k)),
        zero_batch(cfg.max_length, (1, 2)),
    )
    gen_args = argparse.Namespace(tenants=tenants, N=cfg.n, K=cfg.k, seed=7)
    out = {}
    for sched in ("continuous", "microbatch"):
        engine = InferenceEngine(
            model, params, cfg, tok, scheduler=sched, buckets=(1, 2, 4, 8),
            trace_sample=0.25,
        )
        try:
            pools = _pools(register_tenants(engine, gen_args), cfg.k)
            engine.warmup()
            by_tenant, _errs, wall, _retries = run_closed(
                engine, pools, concurrency, seconds,
                np.random.default_rng(0),
            )
            flat = _flat(by_tenant)
            snap = engine.stats.snapshot()
            out[sched] = {
                "qps": round(len(flat) / wall, 1),
                "p50_ms": round(pct(flat, 50), 2) if flat else None,
                "p99_ms": round(pct(flat, 99), 2) if flat else None,
                "occupancy": snap["batch_occupancy"],
                "steady_recompiles": snap["steady_recompiles"],
                # Sampled segment medians + exemplar trace_ids: the A/B
                # attributes the stage (queue/pack/execute/respond), not
                # just the end-to-end number.
                "trace": engine.stats.trace_summary(),
            }
            print(
                f"bench: serving[{sched}]: {out[sched]['qps']} qps, "
                f"p99 {out[sched]['p99_ms']} ms, occupancy "
                f"{out[sched]['occupancy']}",
                file=sys.stderr,
            )
        finally:
            engine.close()
    if out.get("microbatch", {}).get("qps"):
        out["continuous_over_microbatch"] = round(
            out["continuous"]["qps"] / out["microbatch"]["qps"], 3
        )
    return out


def _datapipe_leg(jax, cfg, multi_step, sampler, table, state, n_chips,
                  calls: int = 6):
    """({depth: {feed_stall_frac, eps_per_sec}}, state).

    Each depth gets a FRESH index sampler (same seed — identical episode
    stream, so the work is like-for-like) wrapped in a PipelineFeed
    producing whole fused units with device-put payloads; the timed loop
    is the main bench's hard-synced fused call driven through the feed.
    feed_stall_frac = consumer seconds waiting on the feed / wall seconds
    (depth 0 counts the inline sampling — the fully-serial baseline).
    Threads the donated state back to the caller on every path."""
    from induction_network_on_fewrel_tpu.datapipe import PipelineFeed
    from induction_network_on_fewrel_tpu.native.sampler import (
        make_index_sampler,
    )

    sizes = [
        int(sampler._offsets[i + 1] - sampler._offsets[i])
        for i in range(len(sampler._offsets) - 1)
    ] if hasattr(sampler, "_offsets") else None
    if sizes is None:  # python index sampler fallback
        sizes = list(sampler.sizes)
    S = STEPS_PER_CALL
    out = {}
    for depth in (0, 2, 4):
        feed = PipelineFeed(
            make_index_sampler(
                sizes, cfg.n, cfg.k, cfg.q, batch_size=cfg.batch_size,
                seed=1234,
            ),
            prefetch_depth=depth, unit=S, device_put=True,
        )
        # Per-depth failure isolation INSIDE the leg, so a feed failure
        # between calls drops only that depth and the newest live state
        # still returns to the caller. Not airtight: a multi_step raise
        # AFTER input donation leaves `state` pointing at deleted buffers
        # and the remaining depths (and device-busy leg) fail too — the
        # leg trades that rare mid-call case for correct handling of the
        # realistic between-call feed errors.
        try:
            # Warm the feed's first unit outside the timed window (the
            # main loop's compile is already warm; depth>0 starts its
            # producer here).
            state, metrics = multi_step(state, table, *feed.sample_fused(S))
            _ = float(jax.device_get(metrics["loss"])[-1])
            base_stats = feed.stats()
            t0 = time.monotonic()
            for _ in range(calls):
                state, metrics = multi_step(
                    state, table, *feed.sample_fused(S)
                )
                _ = float(jax.device_get(metrics["loss"])[-1])  # hard sync
            wall = time.monotonic() - t0
            stats = feed.stats()
            stall = stats["stall_s"] - base_stats["stall_s"]
            eps = calls * S * BATCH / wall / max(n_chips, 1)
            out[str(depth)] = {
                "feed_stall_frac": round(stall / wall, 6),
                "eps_per_sec": round(eps, 2),
                "stall_s": round(stall, 4),
                "wall_s": round(wall, 4),
            }
            print(
                f"bench: datapipe depth={depth}: {eps:.0f} eps/s/chip, "
                f"feed stall {100 * stall / wall:.2f}% of wall",
                file=sys.stderr,
            )
        except Exception as e:
            print(
                f"bench: datapipe depth={depth} failed: {e!r}",
                file=sys.stderr,
            )
        finally:
            feed.close()
    return out, state


def _boundary_soak(jax, cfg, fused_call, state, windowed_rate, n_chips,
                   chunks: int = 3):
    """(all-in/windowed ratio, last ring-save payload bytes, state).

    ``chunks`` fused calls each followed by a ring save into a throwaway
    checkpoint dir (tmpfs-staging off: the measurement wants the real
    write), then a durability wait — all-in = episodes / total wall
    including the saves, against the main loop's windowed rate. An
    UNTIMED priming save first absorbs the one-time delta base (warm-soak
    semantics, like compile); the timed saves are the steady-state
    boundary cost — deltas in lazy mode, full elsewhere. The reported
    bytes are the LAST save's payload.

    Failure isolation lives HERE, not in the caller: each fused call
    donates the previous state's buffers, so the caller's binding is
    stale the moment the first call runs — this function must hand back
    the newest live state on EVERY path or the following device-busy
    trace leg would run on deleted buffers.
    """
    import shutil
    import tempfile

    from induction_network_on_fewrel_tpu.train.checkpoint import (
        CheckpointManager,
    )

    tmpdir = tempfile.mkdtemp(prefix="bench_ckpt_")
    mgr = None
    try:
        try:
            mgr = CheckpointManager(tmpdir, cfg, stage="off")
            info = None
            # Priming save: writes the delta BASE (a full save) outside
            # the timed window, as a warm soak's first boundary would.
            mgr.save_latest(1, state, force=True)
            mgr.wait()
            t0 = time.monotonic()
            for i in range(chunks):
                state, metrics = fused_call(state)
                _ = float(jax.device_get(metrics["loss"])[-1])  # hard sync
                # force=True: the measurement is the save cost itself, so
                # the adaptive in-flight skip must not elide it.
                got = mgr.save_latest(
                    int((i + 1) * STEPS_PER_CALL) + 1, state, force=True
                )
                info = got or info
            mgr.wait()
            wall = time.monotonic() - t0
            allin = chunks * STEPS_PER_CALL * BATCH / wall / max(n_chips, 1)
            ratio = round(allin / windowed_rate, 4) if windowed_rate else None
            print(
                f"bench: boundary soak: all-in {allin:.0f} vs windowed "
                f"{windowed_rate:.0f} eps/s/chip -> ratio {ratio} "
                f"(last ring save: {info})",
                file=sys.stderr,
            )
            return ratio, (info or {}).get("bytes"), state
        except Exception as e:  # the soak leg must never sink the bench
            print(f"bench: boundary soak failed: {e!r}", file=sys.stderr)
            return None, None, state
    finally:
        if mgr is not None:
            try:
                mgr.close()
            except Exception as e:
                print(f"bench: ckpt close failed: {e!r}", file=sys.stderr)
        shutil.rmtree(tmpdir, ignore_errors=True)


def _device_busy_fraction(jax, fused_call, state) -> float | None:
    """Trace ONE fused call and return device-busy seconds / wall seconds.

    Busy time = the largest per-line total duration on the device XPlane
    (the "XLA Modules" line — module executions don't overlap on a chip's
    compute stream). Returns None when no device plane exists (CPU runs).
    """
    import glob
    import shutil
    import tempfile

    tmpdir = tempfile.mkdtemp(prefix="bench_xplane_")
    try:
        jax.profiler.start_trace(tmpdir)
        try:
            t0 = time.monotonic()
            state, metrics = fused_call(state)
            _ = float(jax.device_get(metrics["loss"])[-1])  # hard sync
            wall = time.monotonic() - t0
        finally:
            # Close the global profiler session on EVERY path — a raise
            # here is swallowed by the caller, and an orphaned session
            # writing into the removed tmpdir would poison the rest of
            # the bench.
            jax.profiler.stop_trace()

        files = glob.glob(tmpdir + "/**/*.xplane.pb", recursive=True)
        if not files:
            return None
        data = jax.profiler.ProfileData.from_file(files[0])
        busy_ns = 0
        for plane in data.planes:
            if "/device:" not in plane.name:
                continue
            per_line = [
                sum(e.duration_ns for e in line.events)
                for line in plane.lines
            ]
            busy_ns = max([busy_ns, *per_line]) if per_line else busy_ns
        if busy_ns <= 0:
            return None
        return round(min(busy_ns / 1e9 / wall, 1.0), 4)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
